"""NearestNeighbors estimator/model — exact brute-force kNN on the MXU.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2; the modern RAPIDS Spark-ML line exposes cuML brute-force
NearestNeighbors with this param surface: ``k``, ``inputCol``, ``idCol``).
``fit`` indexes the item set; ``kneighbors(queries)`` returns (distances,
indices) — plus caller ids when ``idCol`` is set, mirroring the
item-id/query-id join the Spark version emits.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    extract_features,
    is_device_array,
)
from spark_rapids_ml_tpu.core.ingest import matrix_like
from spark_rapids_ml_tpu.core.lazy_state import LazyHostState
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.params import Param, Params, gt, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_rows,
    load_metadata,
    save_metadata,
    save_rows,
)
from spark_rapids_ml_tpu.ops.knn import knn, knn_sharded, shard_items
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


# Shared extraction convention lives in core.data; keep the old local name.
_extract_features = extract_features


class _NearestNeighborsParams(Params):
    k = Param("_", "k", "number of neighbors", lambda v: gt(0)(toInt(v)))
    inputCol = Param("_", "inputCol", "features column name", toString)
    idCol = Param("_", "idCol", "optional row-id column name", toString)
    metric = Param("_", "metric", "euclidean, sqeuclidean, or cosine", toString)

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(k=5, inputCol="features", metric="euclidean")

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)

    def getIdCol(self) -> Optional[str]:
        return self.getOrDefault(self.idCol) if self.isDefined(self.idCol) else None

    def getMetric(self) -> str:
        return self.getOrDefault(self.metric)


class NearestNeighbors(_NearestNeighborsParams, Estimator, MLReadable):
    """``NearestNeighbors().setK(8).fit(items).kneighbors(queries)``."""

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setK(self, value: int) -> "NearestNeighbors":
        self.set(self.k, value)
        return self

    def setInputCol(self, value: str) -> "NearestNeighbors":
        self.set(self.inputCol, value)
        return self

    def setIdCol(self, value: str) -> "NearestNeighbors":
        self.set(self.idCol, value)
        return self

    def setMetric(self, value: str) -> "NearestNeighbors":
        if value not in ("euclidean", "sqeuclidean", "cosine"):
            raise ValueError(
                f"metric must be euclidean/sqeuclidean/cosine, got {value!r}"
            )
        self.set(self.metric, value)
        return self

    def setMesh(self, mesh) -> "NearestNeighbors":
        self.mesh = mesh
        return self

    def fit(self, dataset: Any) -> "NearestNeighborsModel":
        """Index the item set (brute force: store + pre-shard). Device
        arrays are indexed in place — no host round trip (VERDICT r3 #1).

        A RE-ITERABLE streaming source (iterator factory / block reader)
        becomes a STREAMED index: items never materialize on device or
        host — each ``kneighbors`` call streams the blocks through the
        running top-k merge (``ops.knn.knn_host_streamed``), so item
        capacity is bounded by the source, not HBM (VERDICT r3 #4)."""
        from spark_rapids_ml_tpu.core.data import (
            is_reiterable_stream,
            is_streaming_source,
        )

        if is_streaming_source(dataset):
            if not is_reiterable_stream(dataset):
                raise ValueError(
                    "a streamed kNN index needs a RE-ITERABLE source (a "
                    "zero-arg iterator factory or a block reader with "
                    ".iter_blocks()), not a one-shot generator"
                )
            if self.mesh is not None:
                raise ValueError(
                    "streamed indexes are single-device; use host "
                    "partitions + a mesh for the sharded index"
                )
            model = NearestNeighborsModel(
                self.uid, None, None, items_stream=dataset
            )
            return self._copyValues(model)
        id_col = self.getIdCol()
        items = matrix_like(_extract_features(dataset, self.getInputCol(), drop=id_col))
        ids = None
        if id_col is not None:
            # idCol set but not extractable => raise rather than silently
            # returning positional indices from kneighbors_ids later.
            if isinstance(dataset, DataFrame):
                if id_col not in dataset.columns:
                    raise ValueError(
                        f"idCol={id_col!r} set, but the dataset has no such column"
                    )
                ids = np.asarray(dataset.select(id_col))
            else:
                try:
                    import pandas as pd
                except ImportError:  # pragma: no cover
                    pd = None
                if pd is not None and isinstance(dataset, pd.DataFrame) and id_col in dataset.columns:
                    ids = dataset[id_col].to_numpy()
                else:
                    raise ValueError(
                        f"idCol={id_col!r} set, but the dataset has no such column"
                    )
        if self.getK() > items.shape[0]:
            raise ValueError(f"k={self.getK()} exceeds item count {items.shape[0]}")
        model = NearestNeighborsModel(self.uid, items, ids, mesh=self.mesh)
        return self._copyValues(model)


class NearestNeighborsModel(_NearestNeighborsParams, Model, LazyHostState):
    """Indexed item set; ``kneighbors`` runs the blocked distance GEMM."""

    def __init__(
        self,
        uid: Optional[str] = None,
        items: Optional[np.ndarray] = None,
        ids: Optional[np.ndarray] = None,
        mesh=None,
        items_stream=None,
    ):
        super().__init__(uid)
        # Device-fitted items stay resident; the host view (`items`)
        # converts lazily.
        self._items_raw = (
            items if items is None or is_device_array(items) else np.asarray(items)
        )
        self._items_np: Optional[np.ndarray] = None
        self.ids = None if ids is None else np.asarray(ids)
        self.mesh = mesh
        self._sharded = None  # lazily cached (items_sharded, mask_sharded)
        self._items_stream = items_stream  # re-iterable beyond-HBM index

    # Host views convert lazily; pickling materializes host state and
    # drops the sharded-index device cache (core/lazy_state.LazyHostState).
    _lazy_host_fields = {"_items_raw": ("_items_np", None)}
    _pickle_clear = ("_sharded",)

    def __getstate__(self):
        # Same contract as _save_impl (ADVICE r4): a streamed-index model
        # must not pickle — cloudpickling (Spark broadcast, UDF closures)
        # would either ship the whole item set the streamed mode exists to
        # avoid, or fail opaquely on an unpicklable reader.
        if self._items_stream is not None:
            raise ValueError(
                "a streamed-index model does not pickle (its items live "
                "in the external source); broadcast/persist the source "
                "instead"
            )
        return super().__getstate__()

    @property
    def items(self) -> Optional[np.ndarray]:
        return self._lazy_host_view("_items_raw")

    def setMesh(self, mesh) -> "NearestNeighborsModel":
        self.mesh = mesh
        self._sharded = None
        return self

    def kneighbors(self, queries: Any, k: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """(distances (nq, k), indices (nq, k)). Indices are row positions in
        the fitted item set; use ``kneighbors_ids`` for idCol-mapped output.
        Device queries against device-fitted items stay entirely on device
        (device results back); host queries keep the numpy contract."""
        if self._items_stream is not None:
            return self._kneighbors_streamed(queries, k)
        if self._items_raw is None:
            raise RuntimeError("model has no indexed items")
        n_items = int(self._items_raw.shape[0])
        k = self.getK() if k is None else k
        if not 1 <= k <= n_items:
            raise ValueError(f"k must be in [1, {n_items}], got {k}")
        q_in = matrix_like(
            _extract_features(queries, self.getInputCol(), drop=self.getIdCol())
        )
        device_q = is_device_array(q_in)
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        qj = q_in.astype(dtype) if device_q else jnp.asarray(q_in, dtype=dtype)
        with TraceRange("knn", TraceColor.PURPLE):
            if self.mesh is not None:
                metric = self.getMetric()
                if self._sharded is None or self._sharded[2] != metric:
                    # One upload of the index (cosine rows pre-normalized by
                    # shard_items), reused across query batches (fit's
                    # "store + pre-shard" promise). Keyed by metric:
                    # re-normalization is baked into the upload.
                    xs, mask = shard_items(
                        self.items.astype(np.dtype(dtype)), self.mesh,
                        metric=metric,
                    )
                    self._sharded = (xs, mask, metric)
                xs, mask, _ = self._sharded
                d, idx = knn_sharded(qj, xs, mask, self.mesh, k=k, metric=metric)
            else:
                items_dev = (
                    self._items_raw.astype(dtype)
                    if is_device_array(self._items_raw)
                    else jnp.asarray(self.items, dtype=dtype)
                )
                d, idx = knn(qj, items_dev, k=k, metric=self.getMetric())
        if device_q:
            return d, idx
        return np.asarray(d), np.asarray(idx)

    def _kneighbors_streamed(self, queries: Any, k: Optional[int]):
        """Beyond-HBM search: one pass over the streamed item blocks with
        a running top-k merge. k validates against the streamed count."""
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.core.data import iter_stream_blocks
        from spark_rapids_ml_tpu.ops.knn import knn_host_streamed

        k = self.getK() if k is None else k
        q_in = matrix_like(
            _extract_features(queries, self.getInputCol(), drop=self.getIdCol())
        )
        device_q = is_device_array(q_in)
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        qj = q_in.astype(dtype) if device_q else jnp.asarray(q_in, dtype=dtype)
        with TraceRange("knn streamed", TraceColor.PURPLE):
            d, idx = knn_host_streamed(
                qj,
                iter_stream_blocks(self._items_stream),
                k=k,
                metric=self.getMetric(),
            )
        if device_q:
            return d, idx
        return np.asarray(d), np.asarray(idx)

    def kneighbors_ids(self, queries: Any, k: Optional[int] = None):
        """(distances, ids) with indices mapped through the fitted idCol."""
        d, idx = self.kneighbors(queries, k)
        if self.ids is None:
            return d, idx
        return d, self.ids[idx]

    def transform(self, dataset: Any) -> Any:
        """Append neighbor indices + distances columns (DataFrame input)."""
        d, idx = self.kneighbors(dataset)
        if isinstance(dataset, DataFrame):
            out = dataset.withColumn("knn_indices", list(idx))
            return out.withColumn("knn_distances", list(d))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out = dataset.copy()
                out["knn_indices"] = list(idx)
                out["knn_distances"] = list(d)
                return out
        except ImportError:  # pragma: no cover
            pass
        return d, idx

    def _save_impl(self, path: str) -> None:
        if self._items_stream is not None:
            raise ValueError(
                "a streamed-index model does not persist (its items live "
                "in the external source); persist the source instead"
            )
        save_metadata(
            self,
            path,
            class_name="com.nvidia.rapids.ml.NearestNeighborsModel",
            extra_metadata={"hasIds": self.ids is not None},
        )
        cols = {"item": ("vector", [r for r in self.items])}
        if self.ids is not None:
            cols["id"] = ("scalar", self.ids.tolist())
        save_rows(path, cols)

    @classmethod
    def _load_impl(cls, path: str) -> "NearestNeighborsModel":
        metadata = load_metadata(path, expected_class="NearestNeighborsModel")
        rows = load_rows(path)
        items = np.stack(rows["item"])
        ids = np.asarray(rows["id"]) if metadata.get("hasIds") else None
        model = cls(metadata["uid"], items, ids)
        get_and_set_params(model, metadata)
        return model
