"""ApproximateNearestNeighbors estimator/model — IVF-Flat on the MXU.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2; the modern RAPIDS Spark-ML line exposes cuML ApproximateNearestNeighbors
with this param surface: ``k``, ``algorithm`` (default "ivfflat"),
``algoParams`` (e.g. ``{"nlist": 50, "nprobe": 20}``), ``metric``,
``inputCol``, ``idCol``). Algorithms: ``ivfflat`` / ``ivfpq`` (kernels in
``ops/ann.py`` — see its docstring for the dense-tensor redesign of cuML's
inverted lists), ``brute`` (exact, delegates to ``ops/knn.py``), and
``brute_approx`` (dense MXU scoring + the TPU-native hardware approximate
top-k, ``lax.approx_min_k``). The measured TPU-first result (BASELINE.md
config 7): at 1M items × 96 dims, ``brute_approx`` answers 10k queries
~3.9× faster than ivfflat at ~0.997 recall — TPU gathers are scalarized
while dense GEMMs ride the systolic array, so the inverted-list
structure that wins on GPUs loses here at resident scales. Under a mesh,
``brute_approx`` runs the hardware per-shard top-k with an exact
cross-shard merge (``ops/knn.knn_sharded(approx=True)``).

BEYOND single-chip HBM the choice is measured, not assumed (BASELINE.md
config 8): a re-iterable block source fits a STREAMED brute index
(``ops/knn.knn_host_streamed`` — running top-k merge, capacity bounded
by the source). The measured crossover is effectively zero: the
compressed resident alternative (``ivfpq`` — the only structure whose
residency shrinks relative to raw items) is so gather-bound on TPU
(~78 q/s at 0.16 recall vs 22.4k q/s streamed-device at 1M×128) that
~20 MB/s of source bandwidth already beats it. The TPU-native
beyond-HBM recipe is therefore streaming (or sharding items across
chips/executors — ``knn_sharded`` / the adapter's
``setIndexMode("sharded")``); ``ivfpq``/``ivfflat`` remain for API
parity with the cuML lineage, not as the scale path.

Metrics: ``euclidean`` / ``sqeuclidean`` natively; ``cosine`` by
L2-normalizing items and queries, under which cosine distance equals half
the squared euclidean distance.

Persistence stores the raw items (+ ids); the IVF index is rebuilt on load
from the persisted ``seed`` — the quantizer is deterministic given (items,
n_lists, seed), so a reloaded model probes identical lists.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    extract_features,
    is_device_array,
)
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.ingest import matrix_like
from spark_rapids_ml_tpu.core.lazy_state import LazyHostState
from spark_rapids_ml_tpu.core.params import Param, Params, gt, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_metadata,
    load_rows,
    save_metadata,
    save_rows,
)
from spark_rapids_ml_tpu.ops.ann import (
    IVFIndex,
    IVFPQIndex,
    ann_search_sharded,
    build_ivf_index,
    build_ivfpq_index,
    dispatch_search,
)
from spark_rapids_ml_tpu.ops.knn import knn, knn_sharded, shard_items
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange

_ALGORITHMS = ("ivfflat", "ivfpq", "brute", "brute_approx")


@partial(jax.jit, static_argnames=("k", "block_q"))
def _refine_exact(q, items, cand_idx, k, block_q: int = 1024):
    """Re-rank PQ candidates with exact squared distances.

    Queries stream in ``block_q`` chunks (same memory discipline as the
    searches — an unblocked (nq, k', d) gather would OOM large batches).
    ``cand_idx`` (nq, k') may contain -1 fill slots; those stay at +inf.
    Returns ascending (d2 (nq, k), idx (nq, k))."""
    nq = q.shape[0]
    n_blocks = -(-nq // block_q)
    pad = n_blocks * block_q - nq
    qp = jnp.pad(q, ((0, pad), (0, 0)))
    cp = jnp.pad(cand_idx, ((0, pad), (0, 0)), constant_values=-1)

    def one_block(args):
        qb, cb = args
        gathered = items[jnp.maximum(cb, 0)]  # (Bq, k', d)
        diff = qb[:, None, :] - gathered
        d2 = jnp.sum(diff * diff, axis=2)
        d2 = jnp.where(cb >= 0, d2, jnp.inf)
        neg_top, pos = jax.lax.top_k(-d2, k)
        return -neg_top, jnp.take_along_axis(cb, pos, axis=1)

    d2, idx = jax.lax.map(
        one_block,
        (qp.reshape(n_blocks, block_q, -1), cp.reshape(n_blocks, block_q, -1)),
    )
    return d2.reshape(-1, k)[:nq], idx.reshape(-1, k)[:nq]
_METRICS = ("euclidean", "sqeuclidean", "cosine")


def _dtype():
    return np.float64 if jax.config.jax_enable_x64 else np.float32


def _normalize(x: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    return x / np.maximum(norms, 1e-30)


class _ANNParams(Params):
    k = Param("_", "k", "number of neighbors", lambda v: gt(0)(toInt(v)))
    algorithm = Param(
        "_", "algorithm", "ivfflat | ivfpq | brute | brute_approx", toString
    )
    algoParams = Param(
        "_", "algoParams", "algorithm tuning dict, e.g. {'nlist': 50, 'nprobe': 20}",
        lambda v: dict(v) if v is not None else {},
    )
    metric = Param("_", "metric", "euclidean, sqeuclidean, or cosine", toString)
    inputCol = Param("_", "inputCol", "features column name", toString)
    idCol = Param("_", "idCol", "optional row-id column name", toString)
    seed = Param("_", "seed", "quantizer random seed", toInt)

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            k=5, algorithm="ivfflat", algoParams={}, metric="euclidean",
            inputCol="features", seed=0,
        )

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getAlgorithm(self) -> str:
        return self.getOrDefault(self.algorithm)

    def getAlgoParams(self) -> Dict[str, Any]:
        return self.getOrDefault(self.algoParams)

    def getMetric(self) -> str:
        return self.getOrDefault(self.metric)

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)

    def getIdCol(self) -> Optional[str]:
        return self.getOrDefault(self.idCol) if self.isDefined(self.idCol) else None

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)


class ApproximateNearestNeighbors(_ANNParams, Estimator, MLReadable):
    """``ApproximateNearestNeighbors().setK(8).setAlgoParams({"nlist": 64,
    "nprobe": 8}).fit(items).kneighbors(queries)``."""

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setMesh(self, mesh) -> "ApproximateNearestNeighbors":
        self.mesh = mesh
        return self

    def setK(self, value: int) -> "ApproximateNearestNeighbors":
        self.set(self.k, value)
        return self

    def setAlgorithm(self, value: str) -> "ApproximateNearestNeighbors":
        if value not in _ALGORITHMS:
            raise ValueError(f"algorithm must be one of {_ALGORITHMS}, got {value!r}")
        self.set(self.algorithm, value)
        return self

    def setAlgoParams(self, value: Dict[str, Any]) -> "ApproximateNearestNeighbors":
        known = {
            "nlist", "nprobe", "kmeans_iters", "M", "n_bits", "pq_iters",
            "refine_ratio",
        }
        unknown = set(value) - known
        if unknown:
            raise ValueError(f"unknown algoParams {sorted(unknown)}; known: {sorted(known)}")
        self.set(self.algoParams, value)
        return self

    def setMetric(self, value: str) -> "ApproximateNearestNeighbors":
        if value not in _METRICS:
            raise ValueError(f"metric must be one of {_METRICS}, got {value!r}")
        self.set(self.metric, value)
        return self

    def setInputCol(self, value: str) -> "ApproximateNearestNeighbors":
        self.set(self.inputCol, value)
        return self

    def setIdCol(self, value: str) -> "ApproximateNearestNeighbors":
        self.set(self.idCol, value)
        return self

    def setSeed(self, value: int) -> "ApproximateNearestNeighbors":
        self.set(self.seed, value)
        return self

    def fit(self, dataset: Any) -> "ApproximateNearestNeighborsModel":
        """Device arrays are indexed in place for the brute paths — no
        host round trip (VERDICT r3 #1). IVF builds still pull the items
        to host ONCE (transiently) for the inverted-list packing, which
        is host-side by design (ops/ann.build_ivf_index).

        A RE-ITERABLE streaming source becomes a STREAMED brute index
        (``brute``/``brute_approx`` only): items never materialize — each
        search streams blocks through the running top-k merge, so item
        capacity is bounded by the source, not HBM (VERDICT r3 #4).
        Inverted lists need the resident (compressed) index; see
        BASELINE.md config 8 for the measured streaming-vs-ivfpq
        crossover."""
        from spark_rapids_ml_tpu.core.data import (
            is_reiterable_stream,
            is_streaming_source,
        )

        if is_streaming_source(dataset):
            if not is_reiterable_stream(dataset):
                raise ValueError(
                    "a streamed ANN index needs a RE-ITERABLE source (a "
                    "zero-arg iterator factory or a block reader with "
                    ".iter_blocks()), not a one-shot generator"
                )
            if self.getAlgorithm() not in ("brute", "brute_approx"):
                raise ValueError(
                    "streamed indexes support brute/brute_approx only — "
                    "inverted lists are resident structures (use ivfpq "
                    "for compressed residency)"
                )
            if self.mesh is not None:
                raise ValueError(
                    "streamed indexes are single-device; use host "
                    "partitions + a mesh for the sharded index"
                )
            model = ApproximateNearestNeighborsModel(
                self.uid, None, None, items_stream=dataset
            )
            return self._copyValues(model)
        id_col = self.getIdCol()
        items = matrix_like(extract_features(dataset, self.getInputCol(), drop=id_col))
        ids = None
        if id_col is not None:
            if isinstance(dataset, DataFrame):
                if id_col not in dataset.columns:
                    raise ValueError(
                        f"idCol={id_col!r} set, but the dataset has no such column"
                    )
                ids = np.asarray(dataset.select(id_col))
            else:
                try:
                    import pandas as pd
                except ImportError:  # pragma: no cover
                    pd = None
                if (
                    pd is not None
                    and isinstance(dataset, pd.DataFrame)
                    and id_col in dataset.columns
                ):
                    ids = dataset[id_col].to_numpy()
                else:
                    raise ValueError(
                        f"idCol={id_col!r} set, but the dataset has no such column"
                    )
        if self.getK() > items.shape[0]:
            raise ValueError(f"k={self.getK()} exceeds item count {items.shape[0]}")
        model = ApproximateNearestNeighborsModel(
            self.uid, items, ids, mesh=self.mesh
        )
        model = self._copyValues(model)
        if model.getAlgorithm() in ("ivfflat", "ivfpq"):
            with TraceRange("ann build index", TraceColor.YELLOW):
                model._build_index()
        return model


class ApproximateNearestNeighborsModel(_ANNParams, Model, LazyHostState):
    """Indexed item set; ``kneighbors`` probes the IVF lists.

    With a mesh, queries shard over the data axis against the replicated
    index (:func:`ops.ann.ann_search_sharded`)."""

    def __init__(
        self,
        uid: Optional[str] = None,
        items: Optional[np.ndarray] = None,
        ids: Optional[np.ndarray] = None,
        mesh=None,
        items_stream=None,
    ):
        super().__init__(uid)
        self.mesh = mesh
        self._items_stream = items_stream  # re-iterable beyond-HBM index
        # Device-fitted items stay resident; the host view (`items`)
        # converts lazily.
        self._items_raw = (
            items if items is None or is_device_array(items) else np.asarray(items)
        )
        self._items_np: Optional[np.ndarray] = None
        self.ids = None if ids is None else np.asarray(ids)
        self._index: Optional[IVFIndex | IVFPQIndex] = None
        self._items_dev = None  # cached device copy of _search_items()
        self._sharded_brute = None  # cached (items_sharded, mask) for brute+mesh

    # Host views convert lazily; pickling materializes host state and
    # drops the device-side caches (index, sharded copies — rebuilt
    # lazily after load). core/lazy_state.LazyHostState.
    _lazy_host_fields = {"_items_raw": ("_items_np", None)}
    _pickle_clear = ("_items_dev", "_sharded_brute", "_index")

    def __getstate__(self):
        # Same contract as _save_impl (ADVICE r4): a streamed-index model
        # must not pickle — cloudpickling (Spark broadcast, UDF closures)
        # would either ship the whole item set the streamed mode exists to
        # avoid, or fail opaquely on an unpicklable reader.
        if self._items_stream is not None:
            raise ValueError(
                "a streamed-index model does not pickle (its items live "
                "in the external source); broadcast/persist the source "
                "instead"
            )
        return super().__getstate__()

    @property
    def items(self) -> Optional[np.ndarray]:
        return self._lazy_host_view("_items_raw")

    def setMesh(self, mesh) -> "ApproximateNearestNeighborsModel":
        self.mesh = mesh
        self._sharded_brute = None
        return self

    def _effective_nlist(self) -> int:
        n = self.items.shape[0]
        nlist = self.getAlgoParams().get("nlist")
        if nlist is None:
            # cuML-style default: ~sqrt(n) lists, at least 1.
            nlist = max(1, int(np.sqrt(n)))
        return min(int(nlist), n)

    def _effective_nprobe(self, n_lists: int) -> int:
        nprobe = self.getAlgoParams().get("nprobe")
        if nprobe is None:
            nprobe = max(1, n_lists // 8)
        return min(int(nprobe), n_lists)

    def _search_items(self) -> np.ndarray:
        # IVF list packing is host-side by design (ops/ann.py); a device-
        # fitted model pays this pull ONCE at build time as a transient —
        # not through the `items` property, which would retain a second
        # permanent host copy of a matrix already resident in HBM.
        raw = self._items_raw
        host = np.asarray(raw) if is_device_array(raw) else self.items
        items = host.astype(_dtype(), copy=False)
        return _normalize(items) if self.getMetric() == "cosine" else items

    def _search_items_device(self):
        """Device copy of the (normalized) items, computed once — repeated
        kneighbors calls must not redo the O(n*d) host normalize+transfer.
        Device-fitted items normalize on device (no host round trip)."""
        if self._items_dev is None:
            raw = self._items_raw
            if is_device_array(raw):
                it = raw.astype(_dtype())
                if self.getMetric() == "cosine":
                    it = it / jnp.maximum(
                        jnp.linalg.norm(it, axis=1, keepdims=True), 1e-30
                    )
                self._items_dev = it
            else:
                self._items_dev = jnp.asarray(self._search_items())
        return self._items_dev

    def _effective_m(self, d: int) -> int:
        m = self.getAlgoParams().get("M")
        if m is not None:
            # An EXPLICIT M must divide d — silently retuning a user's
            # compression setting would contradict build_ivfpq_index, which
            # raises for the same input.
            return int(m)
        # cuML-style auto default: ~d/4-dim subspaces, nudged to divide d.
        m = max(1, d // 4)
        while m > 1 and d % m != 0:
            m -= 1
        return m

    def _build_index(self) -> None:
        # With a mesh, the BUILD is distributed too: the coarse quantizer
        # and PQ codebook Lloyds shard their rows over the data axis
        # (previously only the search side was sharded).
        params = self.getAlgoParams()
        if self.getAlgorithm() == "ivfpq":
            self._index = build_ivfpq_index(
                self._search_items(),
                n_lists=self._effective_nlist(),
                m_subspaces=self._effective_m(self.items.shape[1]),
                n_bits=int(params.get("n_bits", 8)),
                seed=self.getSeed(),
                kmeans_iters=int(params.get("kmeans_iters", 10)),
                pq_iters=int(params.get("pq_iters", 10)),
                mesh=self.mesh,
            )
        else:
            self._index = build_ivf_index(
                self._search_items(),
                n_lists=self._effective_nlist(),
                seed=self.getSeed(),
                kmeans_iters=int(params.get("kmeans_iters", 10)),
                mesh=self.mesh,
            )

    def kneighbors(
        self, queries: Any, k: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(distances (nq, k), indices (nq, k)) under the configured metric.

        Unfilled slots when the probed lists hold fewer than k real
        candidates are (inf, -1); raise nprobe/nlist to avoid them.
        """
        if self._items_stream is not None:
            return self._kneighbors_streamed(queries, k)
        if self._items_raw is None:
            raise RuntimeError("model has no indexed items")
        n_items = int(self._items_raw.shape[0])
        k = self.getK() if k is None else k
        if not 1 <= k <= n_items:
            raise ValueError(f"k must be in [1, {n_items}], got {k}")
        metric = self.getMetric()
        q_in = matrix_like(
            extract_features(queries, self.getInputCol(), drop=self.getIdCol())
        )
        device_q = is_device_array(q_in)
        if device_q:
            # Device queries stay resident: normalize on device, results
            # return as device arrays (VERDICT r3 #1).
            q = q_in.astype(_dtype())
            if metric == "cosine":
                q = q / jnp.maximum(
                    jnp.linalg.norm(q, axis=1, keepdims=True), 1e-30
                )
        else:
            q = np.asarray(q_in).astype(_dtype(), copy=False)
            if metric == "cosine":
                q = _normalize(q)

        with TraceRange("ann search", TraceColor.PURPLE):
            if self.getAlgorithm() in ("brute", "brute_approx"):
                # knn's sqeuclidean output matches ivf_search's; the shared
                # metric post-processing below then applies to both paths.
                if self.mesh is not None:
                    # Items shard over the mesh (memory / device count),
                    # exactly as NearestNeighborsModel does.
                    if self._sharded_brute is None:
                        self._sharded_brute = shard_items(
                            self._search_items(), self.mesh
                        )
                    xs, mask = self._sharded_brute
                    d2_j, idx_j = knn_sharded(
                        jnp.asarray(q, dtype=xs.dtype), xs, mask, self.mesh,
                        k=k,
                        approx=self.getAlgorithm() == "brute_approx",
                    )
                else:
                    d2_j, idx_j = knn(
                        jnp.asarray(q), self._search_items_device(), k=k,
                        metric="sqeuclidean",
                        approx=self.getAlgorithm() == "brute_approx",
                    )
            else:
                if self._index is None:
                    self._build_index()
                n_probe = self._effective_nprobe(self._index.n_lists)

                def _fetch(k_fetch: int):
                    if self.mesh is not None:
                        # Queries shard over the mesh against the
                        # replicated index; results are per-query, so no
                        # cross-device merge is needed.
                        return ann_search_sharded(
                            self.mesh, self._index, jnp.asarray(q),
                            k=k_fetch, n_probe=n_probe,
                        )
                    return dispatch_search(self._index)(
                        self._index, jnp.asarray(q), k=k_fetch, n_probe=n_probe
                    )

                if isinstance(self._index, IVFPQIndex):
                    # Refine (FAISS IndexRefineFlat / cuML refine_ratio):
                    # over-fetch candidates under the quantized metric, then
                    # re-rank that shortlist with exact distances — recovers
                    # most of the recall PQ noise costs, at k*ratio exact
                    # distance computations per query.
                    ratio = int(self.getAlgoParams().get("refine_ratio", 1))
                    k_fetch = min(max(k * max(ratio, 1), k), n_items)
                    d2_j, idx_j = _fetch(k_fetch)
                    if k_fetch > k:
                        d2_j, idx_j = _refine_exact(
                            jnp.asarray(q),
                            self._search_items_device(),
                            idx_j,
                            k,
                        )
                else:
                    d2_j, idx_j = _fetch(k)

        if device_q:
            # Device in, device out — metric post-processing on device.
            if metric == "euclidean":
                return jnp.sqrt(d2_j), idx_j
            if metric == "cosine":
                return d2_j / 2.0, idx_j
            return d2_j, idx_j
        d2, idx = np.asarray(d2_j), np.asarray(idx_j)
        if metric == "euclidean":
            return np.sqrt(d2), idx
        if metric == "cosine":
            return d2 / 2.0, idx
        return d2, idx

    def _kneighbors_streamed(self, queries: Any, k: Optional[int]):
        """Beyond-HBM search: one pass over the streamed item blocks with
        a running (approximate) top-k merge."""
        from spark_rapids_ml_tpu.core.data import iter_stream_blocks
        from spark_rapids_ml_tpu.ops.knn import knn_host_streamed

        k = self.getK() if k is None else k
        metric = self.getMetric()
        q_in = matrix_like(
            extract_features(queries, self.getInputCol(), drop=self.getIdCol())
        )
        device_q = is_device_array(q_in)
        qj = (
            q_in.astype(_dtype())
            if device_q
            else jnp.asarray(np.asarray(q_in).astype(_dtype(), copy=False))
        )
        with TraceRange("ann streamed search", TraceColor.PURPLE):
            d, idx = knn_host_streamed(
                qj,
                iter_stream_blocks(self._items_stream),
                k=k,
                metric="sqeuclidean" if metric != "cosine" else "cosine",
                approx=self.getAlgorithm() == "brute_approx",
            )
            if metric == "euclidean":
                d = jnp.sqrt(d)
        if device_q:
            return d, idx
        return np.asarray(d), np.asarray(idx)

    def kneighbors_ids(self, queries: Any, k: Optional[int] = None):
        """(distances, ids) mapped through the fitted idCol; -1 slots stay -1."""
        d, idx = self.kneighbors(queries, k)
        if self.ids is None:
            return d, idx
        mapped = np.where(idx >= 0, self.ids[np.clip(idx, 0, None)], -1)
        return d, mapped

    def transform(self, dataset: Any) -> Any:
        """Append neighbor indices + distances columns (DataFrame input)."""
        d, idx = self.kneighbors(dataset)
        if isinstance(dataset, DataFrame):
            out = dataset.withColumn("ann_indices", list(idx))
            return out.withColumn("ann_distances", list(d))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out = dataset.copy()
                out["ann_indices"] = list(idx)
                out["ann_distances"] = list(d)
                return out
        except ImportError:  # pragma: no cover
            pass
        return d, idx

    def _save_impl(self, path: str) -> None:
        if self._items_stream is not None:
            raise ValueError(
                "a streamed-index model does not persist (its items live "
                "in the external source); persist the source instead"
            )
        save_metadata(
            self,
            path,
            class_name="com.nvidia.rapids.ml.ApproximateNearestNeighborsModel",
            extra_metadata={"hasIds": self.ids is not None},
        )
        cols = {"item": ("vector", [r for r in self.items])}
        if self.ids is not None:
            cols["id"] = ("scalar", self.ids.tolist())
        save_rows(path, cols)

    @classmethod
    def _load_impl(cls, path: str) -> "ApproximateNearestNeighborsModel":
        metadata = load_metadata(path, expected_class="ApproximateNearestNeighborsModel")
        rows = load_rows(path)
        items = np.stack(rows["item"])
        ids = np.asarray(rows["id"]) if metadata.get("hasIds") else None
        model = cls(metadata["uid"], items, ids)
        get_and_set_params(model, metadata)
        # The index is rebuilt lazily on first kneighbors; deterministic
        # given (items, nlist, seed), so probing matches the saved model.
        return model
