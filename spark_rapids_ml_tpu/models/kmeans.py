"""KMeans estimator/model — Spark ML surface, XLA compute.

Param surface mirrors ``org.apache.spark.ml.clustering.KMeans``:
``k``, ``initMode`` ("k-means||" or "random"), ``maxIter``, ``tol``,
``seed``, ``distanceMeasure`` ("euclidean" | "cosine"), ``featuresCol``,
``predictionCol``. This is a beyond-the-reference capability (BASELINE.md
config 3); the reference repo ships only PCA, so the oracle for tests is
scipy/numpy Lloyd rather than a reference file.

"k-means||" routes to on-device k-means++ (the sequential D^2 sampler is
exact; Spark's parallel variant is an approximation of it designed for
multi-pass RDD scans that a jitted fori_loop doesn't need).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import DataFrame, as_matrix, extract_features, extract_weights
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.params import Param, Params, gt, toFloat, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_metadata,
    load_rows,
    save_metadata,
    save_rows,
)
from spark_rapids_ml_tpu.ops.kmeans import (
    assign_clusters,
    kmeans_plusplus_init,
    lloyd,
    normalize_rows,
    random_init,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, shard_rows, weights_as_mask
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


class _KMeansParams(Params):
    k = Param("_", "k", "number of clusters", lambda v: gt(1)(toInt(v)))
    initMode = Param("_", "initMode", "initialization: k-means|| or random", toString)
    maxIter = Param("_", "maxIter", "maximum Lloyd iterations", toInt)
    tol = Param("_", "tol", "center-movement convergence tolerance", toFloat)
    seed = Param("_", "seed", "random seed", toInt)
    distanceMeasure = Param("_", "distanceMeasure", "euclidean or cosine", toString)
    featuresCol = Param("_", "featuresCol", "features column name", toString)
    predictionCol = Param("_", "predictionCol", "prediction column name", toString)
    weightCol = Param("_", "weightCol", "per-row weight column name", toString)

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            k=2,
            initMode="k-means||",
            maxIter=20,
            tol=1e-4,
            seed=0,
            distanceMeasure="euclidean",
            featuresCol="features",
            predictionCol="prediction",
        )

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getInitMode(self) -> str:
        return self.getOrDefault(self.initMode)

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)

    def getDistanceMeasure(self) -> str:
        return self.getOrDefault(self.distanceMeasure)

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def getWeightCol(self) -> Optional[str]:
        return (
            self.getOrDefault(self.weightCol)
            if self.isDefined(self.weightCol)
            else None
        )


class KMeans(_KMeansParams, Estimator, MLReadable):
    """``KMeans().setK(8).fit(x)`` — Lloyd on the MXU."""

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setK(self, value: int) -> "KMeans":
        self.set(self.k, value)
        return self

    def setInitMode(self, value: str) -> "KMeans":
        if value not in ("k-means||", "random"):
            raise ValueError(f"initMode must be 'k-means||' or 'random', got {value!r}")
        self.set(self.initMode, value)
        return self

    def setMaxIter(self, value: int) -> "KMeans":
        self.set(self.maxIter, value)
        return self

    def setTol(self, value: float) -> "KMeans":
        self.set(self.tol, value)
        return self

    def setSeed(self, value: int) -> "KMeans":
        self.set(self.seed, value)
        return self

    def setDistanceMeasure(self, value: str) -> "KMeans":
        if value not in ("euclidean", "cosine"):
            raise ValueError(f"distanceMeasure must be 'euclidean' or 'cosine', got {value!r}")
        self.set(self.distanceMeasure, value)
        return self

    def setFeaturesCol(self, value: str) -> "KMeans":
        self.set(self.featuresCol, value)
        return self

    def setPredictionCol(self, value: str) -> "KMeans":
        self.set(self.predictionCol, value)
        return self

    def setWeightCol(self, value: str) -> "KMeans":
        self.set(self.weightCol, value)
        return self

    def setMesh(self, mesh) -> "KMeans":
        self.mesh = mesh
        return self

    def setInitialModel(self, value) -> "KMeans":
        """Warm start: begin Lloyd from an existing model's centers (or a
        raw (k, d) array) instead of k-means++/random seeding — the
        resume-after-interruption / refine-a-checkpoint path (mllib's
        ``setInitialModel``, cuML's init array). ``k`` must match."""
        centers = value.clusterCenters() if hasattr(value, "clusterCenters") else value
        centers = np.asarray(centers, dtype=np.float64)
        if centers.ndim != 2:
            # Validate BEFORE assigning: a raising setter must not leave
            # the estimator holding a malformed warm start.
            raise ValueError("initial model/centers must be a (k, d) matrix")
        self._initial_centers = centers
        return self

    _initial_centers = None
    _copy_attrs = ("_initial_centers",)  # survives Params.copy (tuning grids)

    def fit(self, dataset: Any) -> "KMeansModel":
        rows = _extract_features(dataset, self.getFeaturesCol())
        x_host = as_matrix(rows)
        w_host = extract_weights(dataset, self.getWeightCol())
        k = self.getK()
        if k > x_host.shape[0]:
            raise ValueError(f"k={k} exceeds number of rows {x_host.shape[0]}")
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        cosine = self.getDistanceMeasure() == "cosine"
        key = jax.random.key(self.getSeed())

        with TraceRange("kmeans fit", TraceColor.CYAN):
            if self.mesh is not None:
                xs, mask, _ = shard_rows(x_host.astype(np.dtype(dtype)), self.mesh)
            else:
                xs = jnp.asarray(x_host, dtype=dtype)
                mask = jnp.ones(xs.shape[0], dtype=dtype)
            if w_host is not None:
                # The row mask doubles as the per-row weight (padding = 0).
                mask = weights_as_mask(w_host, xs.shape[0], np.dtype(dtype), self.mesh)
            if cosine:
                # Zero out padding via the mask's SUPPORT, not its value —
                # fractional weights must not rescale the unit vectors.
                xs = normalize_rows(xs) * (mask > 0).astype(dtype)[:, None]
            if self._initial_centers is not None:
                if self._initial_centers.shape[0] != k:
                    raise ValueError(
                        f"initial model has {self._initial_centers.shape[0]} "
                        f"centers but k={k}"
                    )
                if self._initial_centers.shape[1] != x_host.shape[1]:
                    raise ValueError(
                        f"initial centers have {self._initial_centers.shape[1]} "
                        f"features but the data has {x_host.shape[1]}"
                    )
                init = jnp.asarray(
                    np.pad(
                        self._initial_centers,
                        ((0, 0), (0, xs.shape[1] - x_host.shape[1])),
                    ),
                    dtype=dtype,
                )
                if cosine:
                    init = normalize_rows(init)
            elif self.getInitMode() == "random":
                init = random_init(xs, mask, key, k)
            else:
                init = kmeans_plusplus_init(xs, mask, key, k)
            shards = self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
            centers, cost, n_iter = lloyd(
                xs, mask, init, max_iter=self.getMaxIter(), tol=self.getTol(),
                cosine=cosine, data_shards=shards,
            )

        # Strip model-axis feature padding introduced by shard_rows.
        d = x_host.shape[1]
        model = KMeansModel(
            self.uid,
            np.asarray(centers, dtype=np.float64)[:, :d],
            trainingCost=float(cost),
            numIter=int(n_iter),
        )
        return self._copyValues(model)


# Shared extraction convention; re-exported name kept for back-compat.
_extract_features = extract_features


class KMeansModel(_KMeansParams, Model):
    """Fitted model: ``clusterCenters()`` (k, d), prediction via transform."""

    def __init__(
        self,
        uid: Optional[str] = None,
        clusterCenters: Optional[np.ndarray] = None,
        trainingCost: float = float("nan"),
        numIter: int = 0,
    ):
        super().__init__(uid)
        self._centers = None if clusterCenters is None else np.asarray(clusterCenters)
        self.trainingCost = trainingCost
        self.numIter = numIter

    def clusterCenters(self) -> np.ndarray:
        return self._centers

    def setFeaturesCol(self, value: str) -> "KMeansModel":
        self.set(self.featuresCol, value)
        return self

    def setPredictionCol(self, value: str) -> "KMeansModel":
        self.set(self.predictionCol, value)
        return self

    def predict(self, x) -> np.ndarray:
        if self._centers is None:
            raise RuntimeError("model has no cluster centers")
        x = as_matrix(x)
        centers = self._centers
        if self.getDistanceMeasure() == "cosine":
            x = np.asarray(normalize_rows(jnp.asarray(x)))
            centers = np.asarray(normalize_rows(jnp.asarray(centers)))
        labels, _ = assign_clusters(jnp.asarray(x), jnp.asarray(centers))
        return np.asarray(labels)

    def transform(self, dataset: Any) -> Any:
        rows = _extract_features(dataset, self.getFeaturesCol())
        labels = self.predict(rows)
        if isinstance(dataset, DataFrame):
            return dataset.withColumn(self.getPredictionCol(), list(labels))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out = dataset.copy()
                out[self.getPredictionCol()] = labels
                return out
        except ImportError:  # pragma: no cover
            pass
        return labels

    def computeCost(self, x) -> float:
        """Sum of squared distances to nearest center (Spark's computeCost)."""
        x = as_matrix(x)
        centers = self._centers
        if self.getDistanceMeasure() == "cosine":
            x = np.asarray(normalize_rows(jnp.asarray(x)))
            centers = np.asarray(normalize_rows(jnp.asarray(centers)))
        _, d2 = assign_clusters(jnp.asarray(x), jnp.asarray(centers))
        return float(jnp.sum(d2))

    # --- persistence: Spark KMeansModel layout — one ClusterData row per
    # cluster: (clusterIdx: int, clusterCenter: VectorUDT) ---

    def _save_impl(self, path: str) -> None:
        save_metadata(
            self,
            path,
            class_name="org.apache.spark.ml.clustering.KMeansModel",
            extra_metadata={"trainingCost": self.trainingCost, "numIter": self.numIter},
        )
        save_rows(
            path,
            {
                "clusterIdx": ("scalar", list(range(len(self._centers)))),
                "clusterCenter": ("vector", [c for c in self._centers]),
            },
        )

    @classmethod
    def _load_impl(cls, path: str) -> "KMeansModel":
        metadata = load_metadata(path, expected_class="KMeansModel")
        rows = load_rows(path)
        order = np.argsort(np.asarray(rows["clusterIdx"]))
        centers = np.stack([rows["clusterCenter"][i] for i in order])
        model = cls(
            metadata["uid"],
            centers,
            trainingCost=metadata.get("trainingCost", float("nan")),
            numIter=metadata.get("numIter", 0),
        )
        get_and_set_params(model, metadata)
        return model
