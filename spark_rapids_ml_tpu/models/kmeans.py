"""KMeans estimator/model — Spark ML surface, XLA compute.

Param surface mirrors ``org.apache.spark.ml.clustering.KMeans``:
``k``, ``initMode`` ("k-means||" or "random"), ``maxIter``, ``tol``,
``seed``, ``distanceMeasure`` ("euclidean" | "cosine"), ``featuresCol``,
``predictionCol``. This is a beyond-the-reference capability (BASELINE.md
config 3); the reference repo ships only PCA, so the oracle for tests is
scipy/numpy Lloyd rather than a reference file.

"k-means||" routes to on-device k-means++ (the sequential D^2 sampler is
exact; Spark's parallel variant is an approximation of it designed for
multi-pass RDD scans that a jitted fori_loop doesn't need).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    extract_features,
    extract_weights,
    is_device_array,
    is_streaming_source,
)
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.ingest import matrix_like, prepare_rows
from spark_rapids_ml_tpu.core.lazy_state import LazyHostState
from spark_rapids_ml_tpu.core.params import Param, Params, gt, toFloat, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_metadata,
    load_rows,
    save_metadata,
    save_rows,
)
from spark_rapids_ml_tpu.ops.kmeans import (
    assign_clusters,
    kmeans_plusplus_init,
    lloyd,
    lloyd_resumable,
    normalize_rows,
    random_init,
)
from spark_rapids_ml_tpu.core.serving import (
    note_device_cache,
    serve_blocks,
    serve_rows,
    stream_block_rows,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


def _assign_kernel(x, centers, *, cosine: bool, precision: str = "highest"):
    """Serving kernel: nearest-center labels. Centers follow the batch
    dtype (the model-side cast fuses into the distance GEMM); zero padding
    rows normalize to NaN under cosine but assignments are row-wise, so
    they never reach a real row's label. ``precision`` is the resolved
    serving-family policy mode (ops/precision.py) — part of the static
    dict, so it keys the AOT program cache."""
    centers = centers.astype(x.dtype)
    if cosine:
        x = normalize_rows(x)
        centers = normalize_rows(centers)
    labels, _ = assign_clusters(x, centers, precision=precision)
    return labels


class _KMeansParams(Params):
    k = Param("_", "k", "number of clusters", lambda v: gt(1)(toInt(v)))
    initMode = Param("_", "initMode", "initialization: k-means|| or random", toString)
    maxIter = Param("_", "maxIter", "maximum Lloyd iterations", toInt)
    tol = Param("_", "tol", "center-movement convergence tolerance", toFloat)
    seed = Param("_", "seed", "random seed", toInt)
    distanceMeasure = Param("_", "distanceMeasure", "euclidean or cosine", toString)
    featuresCol = Param("_", "featuresCol", "features column name", toString)
    predictionCol = Param("_", "predictionCol", "prediction column name", toString)
    weightCol = Param("_", "weightCol", "per-row weight column name", toString)
    precision = Param(
        "_", "precision",
        "matmul precision for the Lloyd GEMMs: highest/f32 (6 bf16 passes, "
        "the reference-parity default) | high (3-pass f32-grade) | bf16x3 "
        "(3-pass compensated split, ops/precision.py, max rel err <= 2e-4) "
        "| default/bf16 (1 bf16 pass — bf16-rounded distances flip only "
        "Voronoi-boundary assignments; measured cost delta ~1e-4 relative "
        "at 20Mx16 k=100). Unset, the TPUML_PRECISION[_KMEANS] knobs and "
        "committed autotune decisions apply (resolve_policy layering).",
        toString,
    )
    backend = Param(
        "_", "backend",
        "Lloyd kernel: auto | fused (pallas assignment+stats, zero (n,k) "
        "HBM temporaries) | xla (whole-array fusion)",
        toString,
    )

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            k=2,
            initMode="k-means||",
            maxIter=20,
            tol=1e-4,
            seed=0,
            distanceMeasure="euclidean",
            featuresCol="features",
            predictionCol="prediction",
            precision="highest",
            backend="auto",
        )

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getInitMode(self) -> str:
        return self.getOrDefault(self.initMode)

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)

    def getDistanceMeasure(self) -> str:
        return self.getOrDefault(self.distanceMeasure)

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def getWeightCol(self) -> Optional[str]:
        return (
            self.getOrDefault(self.weightCol)
            if self.isDefined(self.weightCol)
            else None
        )

    def getPrecision(self) -> str:
        return self.getOrDefault(self.precision)

    def getBackend(self) -> str:
        return self.getOrDefault(self.backend)


class KMeans(_KMeansParams, Estimator, MLReadable):
    """``KMeans().setK(8).fit(x)`` — Lloyd on the MXU."""

    # Consumes device arrays in place (prepare_rows), so tuning loops may
    # feed device-resident fold slices (tuning._device_fold_prep).
    _device_foldable = True

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setK(self, value: int) -> "KMeans":
        self.set(self.k, value)
        return self

    def setInitMode(self, value: str) -> "KMeans":
        if value not in ("k-means||", "random"):
            raise ValueError(f"initMode must be 'k-means||' or 'random', got {value!r}")
        self.set(self.initMode, value)
        return self

    def setMaxIter(self, value: int) -> "KMeans":
        self.set(self.maxIter, value)
        return self

    def setTol(self, value: float) -> "KMeans":
        self.set(self.tol, value)
        return self

    def setSeed(self, value: int) -> "KMeans":
        self.set(self.seed, value)
        return self

    def setDistanceMeasure(self, value: str) -> "KMeans":
        if value not in ("euclidean", "cosine"):
            raise ValueError(f"distanceMeasure must be 'euclidean' or 'cosine', got {value!r}")
        self.set(self.distanceMeasure, value)
        return self

    def setFeaturesCol(self, value: str) -> "KMeans":
        self.set(self.featuresCol, value)
        return self

    def setPredictionCol(self, value: str) -> "KMeans":
        self.set(self.predictionCol, value)
        return self

    def setWeightCol(self, value: str) -> "KMeans":
        self.set(self.weightCol, value)
        return self

    def setMesh(self, mesh) -> "KMeans":
        self.mesh = mesh
        return self

    def setPrecision(self, value: str) -> "KMeans":
        from spark_rapids_ml_tpu.ops.precision import validate_mode

        self.set(self.precision, validate_mode(value))
        return self

    def setBackend(self, value: str) -> "KMeans":
        """``"fused"`` computes in float32 (the pallas kernel's dtype) —
        an explicit request downcasts float64 input; ``"auto"`` never
        does (f64 fits keep the XLA path)."""
        if value not in ("auto", "fused", "xla"):
            raise ValueError(f"backend must be auto/fused/xla, got {value!r}")
        self.set(self.backend, value)
        return self

    def setInitialModel(self, value) -> "KMeans":
        """Warm start: begin Lloyd from an existing model's centers (or a
        raw (k, d) array) instead of k-means++/random seeding — the
        resume-after-interruption / refine-a-checkpoint path (mllib's
        ``setInitialModel``, cuML's init array). ``k`` must match."""
        centers = value.clusterCenters() if hasattr(value, "clusterCenters") else value
        centers = np.asarray(centers, dtype=np.float64)
        if centers.ndim != 2:
            # Validate BEFORE assigning: a raising setter must not leave
            # the estimator holding a malformed warm start.
            raise ValueError("initial model/centers must be a (k, d) matrix")
        self._initial_centers = centers
        return self

    _initial_centers = None
    _copy_attrs = ("_initial_centers",)  # survives Params.copy (tuning grids)

    def _fit(self, dataset: Any) -> "KMeansModel":
        rows = _extract_features(dataset, self.getFeaturesCol())
        w_host = extract_weights(dataset, self.getWeightCol())
        if is_streaming_source(rows):
            return self._fit_streaming(rows)
        from spark_rapids_ml_tpu.core import membudget

        # Budgeted admission (core/membudget.py): an over-budget host
        # input reroutes to the SAME _fit_streaming an explicit streaming
        # source takes — bit-identical by construction — and a device OOM
        # mid-fit reclaims caches and takes the same exit.
        can_stream = w_host is None and self.getBackend() != "fused"
        guard = membudget.fit_memory_guard(
            "kmeans", rows, can_stream=can_stream,
            why_cannot_stream="the streaming KMeans path supports neither "
                              "weightCol nor backend='fused'",
            mesh=self.mesh, ledger_families=("kmeans",),
        )
        if guard.degrade:
            return membudget.run_streaming_with_recovery(
                "kmeans", self._fit_streaming, guard.matrix
            )
        fallback = (
            (lambda: membudget.run_streaming_with_recovery(
                "kmeans", self._fit_streaming, membudget.host_matrix(rows)))
            if can_stream and self.mesh is None else None
        )
        return membudget.run_fit_with_oom_recovery(
            "kmeans", lambda: self._fit_in_memory(rows, w_host), fallback
        )

    def _train_precision(self) -> str:
        """Resolve the fit-time GEMM policy (ops/precision.py): an
        explicit ``setPrecision`` wins, then the TPUML_PRECISION[_KMEANS]
        knobs, then a committed autotune decision; otherwise the param's
        default ('highest') stands — bit-identical to the pre-policy
        behavior."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        return resolve_policy("kmeans", requested, default=self.getPrecision())

    def _fit_in_memory(self, rows: Any, w_host) -> "KMeansModel":
        k = self.getK()
        cosine = self.getDistanceMeasure() == "cosine"
        precision = self._train_precision()
        key = jax.random.key(self.getSeed())

        with TraceRange("kmeans fit", TraceColor.CYAN):
            # One funnel for every residence: a jax.Array fits IN PLACE (no
            # host round trip, VERDICT r3 #1), host data places once.
            xs, mask, n, d = prepare_rows(rows, mesh=self.mesh, weights=w_host)
            if k > n:
                raise ValueError(f"k={k} exceeds number of rows {n}")
            dtype = xs.dtype
            if cosine:
                # Zero out padding via the mask's SUPPORT, not its value —
                # fractional weights must not rescale the unit vectors.
                xs = normalize_rows(xs) * (mask > 0).astype(dtype)[:, None]
            if self._initial_centers is not None:
                if self._initial_centers.shape[0] != k:
                    raise ValueError(
                        f"initial model has {self._initial_centers.shape[0]} "
                        f"centers but k={k}"
                    )
                if self._initial_centers.shape[1] != d:
                    raise ValueError(
                        f"initial centers have {self._initial_centers.shape[1]} "
                        f"features but the data has {d}"
                    )
                init = jnp.asarray(
                    np.pad(
                        self._initial_centers,
                        ((0, 0), (0, xs.shape[1] - d)),
                    ),
                    dtype=dtype,
                )
                if cosine:
                    init = normalize_rows(init)
            elif self.getInitMode() == "random":
                # No mesh padding and no weights => every row real: the
                # seeding can use the hardware approximate top-k.
                init = random_init(
                    xs, mask, key, k,
                    assume_unmasked=self.mesh is None and w_host is None,
                )
            else:
                init = kmeans_plusplus_init(xs, mask, key, k)
            # Preemption tolerance (robustness/checkpoint.py): with the
            # TPUML_CHECKPOINT_* knobs set, Lloyd runs segmented with
            # async snapshots and resumes mid-solve from the latest valid
            # checkpoint — except under an EXPLICIT backend='fused'
            # request, whose pallas kernel has no externalized state.
            ckpt = (
                self._fit_checkpointer("kmeans.lloyd", data=(xs, mask, init))
                if self.getBackend() != "fused"
                else None
            )
            if ckpt is not None:
                shards = self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
                centers, cost, n_iter = lloyd_resumable(
                    xs, mask, init, ckpt,
                    max_iter=self.getMaxIter(), tol=self.getTol(),
                    cosine=cosine, data_shards=shards,
                    precision=precision, mesh=self.mesh,
                )
                from spark_rapids_ml_tpu.parallel.distributed import (
                    replicate_for_host,
                )

                centers = replicate_for_host(self.mesh, centers)
                model = KMeansModel(
                    self.uid, centers[:, :d], trainingCost=cost, numIter=n_iter
                )
                return self._copyValues(model)
            backend = self._resolve_backend(
                w_host, int(xs.shape[0]) * k, d=int(xs.shape[1]), k=k,
                dtype=xs.dtype,
            )
            if backend == "fused":
                # Pallas fused assignment+stats: the (n, k) distance and
                # one-hot temporaries never touch HBM (VERDICT r3 #2).
                # Requires a uniform mask (no weightCol) and one device —
                # _resolve_backend guarantees both.
                from spark_rapids_ml_tpu.ops.pallas.kmeans import (
                    auto_block_n,
                    lloyd_fused,
                    packed_feasible,
                    pad_transposed,
                )

                bn = auto_block_n(int(xs.shape[1]), k)
                xt, _ = pad_transposed(xs.astype(jnp.float32), block_n=bn)
                centers, cost, n_iter = lloyd_fused(
                    xt,
                    int(xs.shape[0]),
                    init.astype(jnp.float32),
                    max_iter=self.getMaxIter(),
                    tol=self.getTol(),
                    block_n=bn,
                    precision=precision,
                    cosine=cosine,
                    # Explicit backend='fused' off-TPU runs the pallas
                    # interpreter (tests); auto never routes here off-TPU.
                    interpret=jax.default_backend() != "tpu",
                    # Lane packing: small d x small k shares one MXU tile
                    # across P row blocks (BASELINE.md "KMeans lane
                    # packing": 4.9x on the shape pair, parity-checked).
                    packed=packed_feasible(int(xs.shape[1]), k),
                )
            else:
                shards = self.mesh.shape[DATA_AXIS] if self.mesh is not None else 1
                centers, cost, n_iter = lloyd(
                    xs, mask, init, max_iter=self.getMaxIter(), tol=self.getTol(),
                    cosine=cosine, data_shards=shards,
                    precision=precision,
                )

        # Gang fits can hand back sharded results; host reads (the model's
        # lazy float64 pulls) need them fully replicated on every member.
        from spark_rapids_ml_tpu.parallel.distributed import replicate_for_host

        centers = replicate_for_host(self.mesh, centers)
        # Strip model-axis feature padding (device slice, stays async);
        # host float64 conversion happens lazily inside KMeansModel.
        model = KMeansModel(
            self.uid,
            centers[:, :d],
            trainingCost=cost,
            numIter=n_iter,
        )
        return self._copyValues(model)

    # Fused-kernel auto threshold: below this n*k the whole fit is
    # sub-millisecond either way and the extra transposed copy + pallas
    # compile isn't worth it.
    _FUSED_AUTO_WORK = 1 << 22

    def _resolve_backend(
        self, w_host, work: int, d: int = 1, k: int = 2, dtype=None
    ) -> str:
        """Pick the Lloyd kernel. "fused" needs a uniform row weight (the
        kernel streams no mask — padding is corrected in closed form) and
        a single-device layout; explicit requests that can't be honored
        raise rather than silently fall back. "auto" takes fused for
        eligible large fits (measured never slower, up to ~12% faster at
        matched precision — BASELINE.md KMeans backend table) and keeps
        the XLA path for small ones (no extra transposed copy/compile)."""
        from spark_rapids_ml_tpu.ops.pallas.kmeans import fused_feasible

        requested = self.getBackend()
        blockers = []
        if self.mesh is not None:
            blockers.append("a mesh")
        if w_host is not None:
            blockers.append("weightCol")
        if not fused_feasible(d, k):
            blockers.append(f"d={d} x k={k} (VMEM residents exceed budget)")
        if requested == "fused":
            if blockers:
                raise ValueError(
                    "backend='fused' does not support " + ", ".join(blockers)
                )
            # An EXPLICIT fused request accepts the kernel's documented
            # f32 compute (the setter docs say so) even for f64 input.
            return "fused"
        if dtype is not None and np.dtype(dtype) == np.float64:
            # auto must not silently downcast x64 input to the f32 kernel
            # — precision='highest' on f64 means the f64 XLA path.
            blockers.append("float64 input")
        if requested == "xla" or blockers:
            return "xla"
        # auto: the pallas kernel is TPU-compiled; other platforms would
        # run the (slow) interpreter, so they keep the XLA path.
        if jax.default_backend() != "tpu":
            return "xla"
        return "fused" if work >= self._FUSED_AUTO_WORK else "xla"

    # Seeding-sample reservoir size for streaming fits: big enough that
    # k-means++ on the sample seeds like k-means++ on the data, bounded so
    # the sample never dominates memory.
    _STREAM_SAMPLE_CAP = 4096

    def _fit_streaming(self, rows) -> "KMeansModel":
        """Re-iterable block sources (iterator factory / NpyBlockReader):
        one full data pass per Lloyd iteration at O(block + k*d) memory —
        the multi-pass twin of the streamed PCA sketch (VERDICT r3 #6).
        Seeding runs k-means++ (or random) on a one-pass uniform reservoir.
        """
        from spark_rapids_ml_tpu.core.data import (
            is_reiterable_stream,
            iter_stream_blocks,
        )
        from spark_rapids_ml_tpu.core.ingest import default_dtype
        from spark_rapids_ml_tpu.ops.kmeans import (
            lloyd_streaming,
            reservoir_sample_rows,
        )

        if not is_reiterable_stream(rows):
            raise ValueError(
                "KMeans is multi-pass: a streaming fit needs a RE-ITERABLE "
                "source (a zero-arg iterator factory or a block reader with "
                ".iter_blocks()), not a one-shot generator"
            )
        if self.mesh is not None:
            raise ValueError(
                "streaming KMeans is single-device; pass host partitions "
                "for a mesh fit"
            )
        k = self.getK()
        cosine = self.getDistanceMeasure() == "cosine"
        dtype = np.dtype(default_dtype())
        with TraceRange("kmeans stream fit", TraceColor.CYAN):
            if self._initial_centers is not None:
                # Warm start: no sampling pass — validate the feature
                # width against ONE peeked block (the in-memory path's
                # clear error, not an opaque matmul shape failure) and
                # trust k from the supplied centers.
                from spark_rapids_ml_tpu.core.data import peek_stream_width

                if self._initial_centers.shape[0] != k:
                    raise ValueError(
                        f"initial model has {self._initial_centers.shape[0]} "
                        f"centers but k={k}"
                    )
                width = peek_stream_width(rows)
                if self._initial_centers.shape[1] != width:
                    raise ValueError(
                        f"initial centers have {self._initial_centers.shape[1]} "
                        f"features but the data has {width}"
                    )
                init = jnp.asarray(self._initial_centers, dtype=dtype)
                if cosine:
                    init = normalize_rows(init)
            else:
                cap = max(self._STREAM_SAMPLE_CAP, 4 * k)
                sample, n_seen = reservoir_sample_rows(
                    iter_stream_blocks(rows), cap, self.getSeed(), dtype=dtype
                )
                if k > n_seen:
                    raise ValueError(f"k={k} exceeds number of rows {n_seen}")
                xs = jnp.asarray(sample)
                if cosine:
                    xs = normalize_rows(xs)
                mask = jnp.ones(xs.shape[0], dtype=xs.dtype)
                key = jax.random.key(self.getSeed())
                if self.getInitMode() == "random":
                    init = random_init(xs, mask, key, k)
                else:
                    init = kmeans_plusplus_init(xs, mask, key, k)
            centers, cost, n_iter = lloyd_streaming(
                lambda: iter_stream_blocks(rows),
                init,
                max_iter=self.getMaxIter(),
                tol=self.getTol(),
                precision=self._train_precision(),
                cosine=cosine,
                dtype=dtype,
            )
        model = KMeansModel(
            self.uid, centers, trainingCost=cost, numIter=n_iter
        )
        return self._copyValues(model)


# Shared extraction convention; re-exported name kept for back-compat.
_extract_features = extract_features


class KMeansModel(_KMeansParams, Model, LazyHostState):
    """Fitted model: ``clusterCenters()`` (k, d), prediction via transform.

    Fitted state may be host numpy OR live jax.Arrays from a device-
    resident fit; host float64 views convert lazily and pickling
    materializes host state (core/lazy_state.LazyHostState)."""

    _lazy_host_fields = {"_centers_raw": ("_centers_np", np.float64)}
    _pickle_clear = ("_centers_dev",)

    def __init__(
        self,
        uid: Optional[str] = None,
        clusterCenters: Optional[np.ndarray] = None,
        trainingCost: float = float("nan"),
        numIter: int = 0,
    ):
        super().__init__(uid)
        self._centers_raw = clusterCenters
        self._centers_np: Optional[np.ndarray] = None
        self._centers_dev = None
        self._cost_raw = trainingCost
        self._iter_raw = numIter

    def __getstate__(self):
        state = super().__getstate__()
        state["_cost_raw"] = self.trainingCost
        state["_iter_raw"] = self.numIter
        return state

    @property
    def _centers(self) -> Optional[np.ndarray]:
        return self._lazy_host_view("_centers_raw")

    @property
    def trainingCost(self) -> float:
        if not isinstance(self._cost_raw, float):
            self._cost_raw = float(self._cost_raw)
        return self._cost_raw

    @property
    def numIter(self) -> int:
        if not isinstance(self._iter_raw, int):
            self._iter_raw = int(self._iter_raw)
        return self._iter_raw

    def clusterCenters(self) -> np.ndarray:
        return self._centers

    def _centers_device(self, dtype):
        """Centers as a device array for device-side prediction; free when
        the fit was device-resident (the raw state IS the device array)."""
        raw = self._centers_raw
        if is_device_array(raw) and raw.dtype == dtype:
            return raw
        return jnp.asarray(
            raw if is_device_array(raw) else self._centers, dtype=dtype
        )

    def setFeaturesCol(self, value: str) -> "KMeansModel":
        self.set(self.featuresCol, value)
        return self

    def setPredictionCol(self, value: str) -> "KMeansModel":
        self.set(self.predictionCol, value)
        return self

    def _serving_precision(self) -> str:
        """The serving-family policy mode (ops/precision.py). An explicit
        ``setPrecision`` on the estimator survives into the model via
        param copy and wins; otherwise the TPUML_PRECISION[_SERVING]
        knobs and committed autotune decisions apply. Part of the
        serving static dict, hence of the AOT/program cache key."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        return resolve_policy("serving", requested)

    def predict(self, x) -> np.ndarray:
        if self._centers_raw is None:
            raise RuntimeError("model has no cluster centers")
        x = matrix_like(x)
        static = {
            "cosine": self.getDistanceMeasure() == "cosine",
            "precision": self._serving_precision(),
        }
        # Large HOST batches stream block by block (double-buffered: the
        # H2D of block k+1 overlaps the assignment GEMM of block k —
        # the PCA transform's discipline) instead of paying one
        # serialized whole-matrix transfer.
        if not is_device_array(x):
            xh = np.asarray(x)
            if xh.ndim == 2 and xh.shape[0] > stream_block_rows():
                return serve_blocks(
                    _assign_kernel,
                    xh,
                    (self._centers_serving(),),
                    static=static,
                    name="kmeans.predict",
                )
        # Device queries get device labels (no host pull the caller didn't
        # ask for); host queries keep the numpy contract. Both run through
        # the shape-bucketed serving program cache.
        return serve_rows(
            _assign_kernel,
            x,
            (self._centers_serving(),),
            static=static,
            name="kmeans.predict",
        )

    def _centers_serving(self):
        """Centers as ONE device-resident array reused by every predict —
        the kernel's in-program cast to the batch dtype makes a single
        copy serve all batch dtypes."""
        raw = self._centers_raw
        if is_device_array(raw):
            return raw
        if self._centers_dev is None:
            self._centers_dev = jnp.asarray(self._centers)
            note_device_cache(self)
        return self._centers_dev

    def serving_signature(self):
        """The online-serving contract (serving/signature.py): the same
        assignment kernel ``predict`` routes through the program cache,
        the device-resident centers, and the label output spec the
        admission controller prices requests with."""
        from spark_rapids_ml_tpu.serving.signature import ServingSignature

        if self._centers_raw is None:
            raise RuntimeError("model has no cluster centers")
        centers = self._centers_serving()
        return ServingSignature(
            kernel=_assign_kernel,
            weights=(centers,),
            static={
                "cosine": self.getDistanceMeasure() == "cosine",
                "precision": self._serving_precision(),
            },
            name="kmeans.predict",
            n_features=int(centers.shape[1]),
            output_spec=lambda n, dtype: (
                jax.ShapeDtypeStruct((n,), np.int32),
            ),
        )

    def transform(self, dataset: Any) -> Any:
        rows = _extract_features(dataset, self.getFeaturesCol())
        labels = self.predict(rows)
        if isinstance(dataset, DataFrame):
            return dataset.withColumn(self.getPredictionCol(), list(labels))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out = dataset.copy()
                out[self.getPredictionCol()] = labels
                return out
        except ImportError:  # pragma: no cover
            pass
        return labels

    def copy(self, extra=None) -> "KMeansModel":
        """Model.copy preserves fitted state (Spark's Model.copy contract)."""
        that = KMeansModel(self.uid, self._centers_raw, self._cost_raw, self._iter_raw)
        return self._copyValues(that, extra)

    def computeCost(self, x) -> float:
        """Sum of squared distances to nearest center (Spark's computeCost)."""
        xj = matrix_like(x)
        if not is_device_array(xj):
            xj = jnp.asarray(xj)
        centers = self._centers_device(xj.dtype)
        if self.getDistanceMeasure() == "cosine":
            xj = normalize_rows(xj)
            centers = normalize_rows(centers)
        _, d2 = assign_clusters(xj, centers)
        return float(jnp.sum(d2))

    # --- persistence: Spark KMeansModel layout — one ClusterData row per
    # cluster: (clusterIdx: int, clusterCenter: VectorUDT) ---

    def _save_impl(self, path: str) -> None:
        save_metadata(
            self,
            path,
            class_name="org.apache.spark.ml.clustering.KMeansModel",
            extra_metadata={"trainingCost": self.trainingCost, "numIter": self.numIter},
        )
        save_rows(
            path,
            {
                "clusterIdx": ("scalar", list(range(len(self._centers)))),
                "clusterCenter": ("vector", [c for c in self._centers]),
            },
        )

    @classmethod
    def _load_impl(cls, path: str) -> "KMeansModel":
        metadata = load_metadata(path, expected_class="KMeansModel")
        rows = load_rows(path)
        order = np.argsort(np.asarray(rows["clusterIdx"]))
        centers = np.stack([rows["clusterCenter"][i] for i in order])
        model = cls(
            metadata["uid"],
            centers,
            trainingCost=metadata.get("trainingCost", float("nan")),
            numIter=metadata.get("numIter", 0),
        )
        get_and_set_params(model, metadata)
        return model
