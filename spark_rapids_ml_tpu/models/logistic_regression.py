"""LogisticRegression estimator/model — Spark ML surface, L-BFGS on the MXU.

Param surface mirrors ``org.apache.spark.ml.classification.LogisticRegression``:
``featuresCol``, ``labelCol``, ``predictionCol``, ``probabilityCol``,
``rawPredictionCol``, ``maxIter``, ``regParam``, ``elasticNetParam`` (0 ->
L2 via jitted L-BFGS; > 0 -> L1/elastic net via jitted FISTA, Spark's
OWL-QN analogue), ``tol``, ``fitIntercept``, ``standardization``,
``family`` ("auto" | "binomial" | "multinomial"), ``threshold``.
Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2); the whole optimization is one jitted program (ops.logistic),
mesh-shardable.

Model attributes follow Spark: binomial exposes ``coefficients`` (d,) and
``intercept``; multinomial exposes ``coefficientMatrix`` (numClasses, d) and
``interceptVector`` (numClasses,).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import DataFrame, as_matrix, extract_weights
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.params import Param, Params, toBoolean, toFloat, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_data,
    load_metadata,
    save_data,
    save_metadata,
)
from spark_rapids_ml_tpu.models.linear_regression import _extract_xy
from spark_rapids_ml_tpu.ops.logistic import (
    classification_metrics,
    fit_logistic,
    fit_logistic_elastic_net,
    predict_logistic,
)
from spark_rapids_ml_tpu.parallel.mesh import shard_rows, weights_as_mask
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


class _LogisticRegressionParams(Params):
    featuresCol = Param("_", "featuresCol", "features column name", toString)
    labelCol = Param("_", "labelCol", "label column name", toString)
    predictionCol = Param("_", "predictionCol", "prediction column name", toString)
    probabilityCol = Param("_", "probabilityCol", "class probabilities column", toString)
    rawPredictionCol = Param("_", "rawPredictionCol", "raw logits column", toString)
    maxIter = Param("_", "maxIter", "maximum L-BFGS iterations", toInt)
    regParam = Param("_", "regParam", "L2 regularization strength", toFloat)
    elasticNetParam = Param("_", "elasticNetParam", "L1/L2 mixing (0 = pure L2)", toFloat)
    tol = Param("_", "tol", "gradient-norm convergence tolerance", toFloat)
    fitIntercept = Param("_", "fitIntercept", "whether to fit an intercept", toBoolean)
    standardization = Param(
        "_", "standardization", "optimize in standardized feature space", toBoolean
    )
    family = Param("_", "family", "auto, binomial, or multinomial", toString)
    threshold = Param("_", "threshold", "binary decision threshold", toFloat)
    weightCol = Param("_", "weightCol", "per-row weight column name", toString)

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            maxIter=100,
            regParam=0.0,
            elasticNetParam=0.0,
            tol=1e-6,
            fitIntercept=True,
            standardization=True,
            family="auto",
            threshold=0.5,
        )

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)

    def getStandardization(self) -> bool:
        return self.getOrDefault(self.standardization)

    def getFamily(self) -> str:
        return self.getOrDefault(self.family)

    def getThreshold(self) -> float:
        return self.getOrDefault(self.threshold)

    def getWeightCol(self):
        return (
            self.getOrDefault(self.weightCol)
            if self.isDefined(self.weightCol)
            else None
        )


class LogisticRegression(_LogisticRegressionParams, Estimator, MLReadable):
    """``LogisticRegression().setRegParam(0.1).fit((X, y))``."""

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setFeaturesCol(self, value: str) -> "LogisticRegression":
        self.set(self.featuresCol, value)
        return self

    def setLabelCol(self, value: str) -> "LogisticRegression":
        self.set(self.labelCol, value)
        return self

    def setPredictionCol(self, value: str) -> "LogisticRegression":
        self.set(self.predictionCol, value)
        return self

    def setProbabilityCol(self, value: str) -> "LogisticRegression":
        self.set(self.probabilityCol, value)
        return self

    def setRawPredictionCol(self, value: str) -> "LogisticRegression":
        self.set(self.rawPredictionCol, value)
        return self

    def setMaxIter(self, value: int) -> "LogisticRegression":
        self.set(self.maxIter, value)
        return self

    def setRegParam(self, value: float) -> "LogisticRegression":
        if value < 0:
            raise ValueError(f"regParam must be >= 0, got {value}")
        self.set(self.regParam, value)
        return self

    def setElasticNetParam(self, value: float) -> "LogisticRegression":
        if not 0.0 <= value <= 1.0:
            raise ValueError(f"elasticNetParam must be in [0, 1], got {value}")
        self.set(self.elasticNetParam, value)
        return self

    def setTol(self, value: float) -> "LogisticRegression":
        self.set(self.tol, value)
        return self

    def setFitIntercept(self, value: bool) -> "LogisticRegression":
        self.set(self.fitIntercept, value)
        return self

    def setStandardization(self, value: bool) -> "LogisticRegression":
        self.set(self.standardization, value)
        return self

    def setFamily(self, value: str) -> "LogisticRegression":
        if value not in ("auto", "binomial", "multinomial"):
            raise ValueError(f"family must be auto/binomial/multinomial, got {value!r}")
        self.set(self.family, value)
        return self

    def setThreshold(self, value: float) -> "LogisticRegression":
        self.set(self.threshold, value)
        return self

    def setWeightCol(self, value: str) -> "LogisticRegression":
        self.set(self.weightCol, value)
        return self

    def setMesh(self, mesh) -> "LogisticRegression":
        self.mesh = mesh
        return self

    _initial_weights = None  # (weights (d, c), intercepts (c,)) warm start
    _copy_attrs = ("_initial_weights",)

    def setInitialModel(self, value) -> "LogisticRegression":
        """Warm start the L-BFGS solve from an existing model's solution —
        resume an interrupted fit, or seed a regularization-path sweep
        (each grid cell starts from the previous optimum). Applies to the
        L-BFGS (L2 / unregularized) path."""
        w = np.asarray(value.weights, dtype=np.float64)
        b = np.asarray(value.intercepts, dtype=np.float64)
        if w.ndim != 2 or b.ndim != 1 or w.shape[1] != b.shape[0]:
            raise ValueError("initial model must carry (d, c) weights and (c,) intercepts")
        self._initial_weights = (w, b)
        return self

    def fit(self, dataset: Any) -> "LogisticRegressionModel":
        x_host, y_host = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        w_host = extract_weights(dataset, self.getWeightCol())
        y_int = y_host.astype(np.int64)
        if not np.array_equal(y_int, y_host):
            raise ValueError("labels must be integers in [0, numClasses)")
        if np.any(y_int < 0):
            raise ValueError("labels must be >= 0")
        n_classes = int(y_int.max()) + 1
        family = self.getFamily()
        if family == "auto":
            family = "binomial" if n_classes <= 2 else "multinomial"
        if family == "binomial" and n_classes > 2:
            raise ValueError(f"binomial family with {n_classes} labels")
        n_classes = max(n_classes, 2)
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

        with TraceRange("logreg fit", TraceColor.YELLOW):
            if self.mesh is not None:
                xs, mask, _ = shard_rows(x_host.astype(np.dtype(dtype)), self.mesh)
                y_pad = np.zeros(xs.shape[0], dtype=np.int32)
                y_pad[: len(y_int)] = y_int
                from jax.sharding import NamedSharding, PartitionSpec as P
                from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

                ys = jax.device_put(y_pad, NamedSharding(self.mesh, P(DATA_AXIS)))
            else:
                xs = jnp.asarray(x_host, dtype=dtype)
                ys = jnp.asarray(y_int, dtype=jnp.int32)
                mask = jnp.ones(xs.shape[0], dtype=dtype)
            if w_host is not None:
                # The row mask doubles as the per-row weight (padding = 0).
                mask = weights_as_mask(w_host, xs.shape[0], np.dtype(dtype), self.mesh)
            use_multinomial = family == "multinomial"
            enet = self.getElasticNetParam()
            # regParam == 0 means zero effective penalty whatever enet says:
            # use the L-BFGS path (faster, and it applies the multinomial
            # identifiability pivot the proximal path has no need for).
            init_w = init_b = None
            if self._initial_weights is not None:
                w0, b0 = self._initial_weights
                c_expect = n_classes if (use_multinomial or n_classes > 2) else 1
                if w0.shape != (x_host.shape[1], c_expect):
                    raise ValueError(
                        f"initial model weights {w0.shape} != expected "
                        f"({x_host.shape[1]}, {c_expect})"
                    )
                # Pad to any model-axis feature padding the mesh added.
                pad_d = xs.shape[1] - w0.shape[0]
                init_w = jnp.asarray(
                    np.pad(w0, ((0, pad_d), (0, 0))), dtype=dtype
                )
                init_b = jnp.asarray(b0, dtype=dtype)
            if enet == 0.0 or self.getRegParam() == 0.0:
                result = fit_logistic(
                    xs,
                    ys,
                    mask,
                    n_classes=n_classes,
                    reg_param=self.getRegParam(),
                    fit_intercept=self.getFitIntercept(),
                    standardization=self.getStandardization(),
                    max_iter=self.getMaxIter(),
                    tol=self.getTol(),
                    multinomial=use_multinomial,
                    init_w=init_w,
                    init_b=init_b,
                )
            else:
                if self._initial_weights is not None:
                    raise ValueError(
                        "setInitialModel warm start applies to the L-BFGS "
                        "path (elasticNetParam 0 or regParam 0)"
                    )
                # L1/elastic net: FISTA (Spark reaches this via OWL-QN).
                # maxIter caps proximal iterations exactly as it caps
                # OWL-QN iterations in Spark — users of the slower-
                # converging proximal steps raise maxIter, preserving the
                # totalIterations <= maxIter invariant.
                result = fit_logistic_elastic_net(
                    xs,
                    ys,
                    mask,
                    n_classes=n_classes,
                    reg_param=self.getRegParam(),
                    elastic_net_param=enet,
                    fit_intercept=self.getFitIntercept(),
                    standardization=self.getStandardization(),
                    max_iter=self.getMaxIter(),
                    tol=self.getTol(),
                    multinomial=use_multinomial,
                )
            weights = np.asarray(result.weights)
            intercepts = np.asarray(result.intercepts)

        # Strip model-axis feature padding introduced by shard_rows.
        d = x_host.shape[1]
        model = LogisticRegressionModel(
            self.uid,
            weights[:d].astype(np.float64),
            intercepts.astype(np.float64),
            numClasses=n_classes,
            numIter=int(result.n_iter),
        )
        return self._copyValues(model)


class LogisticRegressionModel(_LogisticRegressionParams, Model):
    """Fitted model. ``weights``: (d, 1) binomial sigmoid column or (d, c)
    softmax matrix; ``intercepts``: (1,) or (c,)."""

    def __init__(
        self,
        uid: Optional[str] = None,
        weights: Optional[np.ndarray] = None,
        intercepts: Optional[np.ndarray] = None,
        numClasses: int = 2,
        numIter: int = 0,
    ):
        super().__init__(uid)
        self.weights = None if weights is None else np.asarray(weights)
        self.intercepts = None if intercepts is None else np.asarray(intercepts)
        self.numClasses = numClasses
        self.numIter = numIter

    def setFeaturesCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.featuresCol, value)
        return self

    def setPredictionCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.predictionCol, value)
        return self

    def setProbabilityCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.probabilityCol, value)
        return self

    def setRawPredictionCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.rawPredictionCol, value)
        return self

    def setThreshold(self, value: float) -> "LogisticRegressionModel":
        self.set(self.threshold, value)
        return self

    def copy(self, extra=None) -> "LogisticRegressionModel":
        that = LogisticRegressionModel(
            self.uid, self.weights, self.intercepts, self.numClasses, self.numIter
        )
        return self._copyValues(that, extra)

    # --- Spark-style accessors ---

    @property
    def coefficients(self) -> np.ndarray:
        """Binomial coefficient vector (d,). Raises for multinomial (Spark
        throws the same way)."""
        if self.weights.shape[1] != 1:
            raise AttributeError("multinomial model: use coefficientMatrix")
        return self.weights[:, 0]

    @property
    def intercept(self) -> float:
        if self.intercepts.shape[0] != 1:
            raise AttributeError("multinomial model: use interceptVector")
        return float(self.intercepts[0])

    @property
    def coefficientMatrix(self) -> np.ndarray:
        """Spark's orientation: (1, d) for binomial, (numClasses, d) for
        multinomial."""
        return self.weights.T

    @property
    def interceptVector(self) -> np.ndarray:
        return self.intercepts.copy()

    def predict(self, x) -> np.ndarray:
        labels, _, _ = self._predict_all(as_matrix(x))
        return labels

    def predictProbability(self, x) -> np.ndarray:
        _, probs, _ = self._predict_all(as_matrix(x))
        return probs

    def predictRaw(self, x) -> np.ndarray:
        """Raw margins (Spark's rawPrediction): [-z, z] for binomial,
        the logits for multinomial — NOT probabilities."""
        _, _, raw = self._predict_all(as_matrix(x))
        return raw

    def _predict_all(self, x: np.ndarray):
        """One forward pass; binomial labels honor the threshold param."""
        labels, probs, raw = predict_logistic(
            jnp.asarray(x, dtype=jnp.asarray(self.weights).dtype),
            jnp.asarray(self.weights),
            jnp.asarray(self.intercepts),
            n_classes=self.numClasses,
        )
        labels, probs = np.asarray(labels), np.asarray(probs)
        if self.weights.shape[1] == 1 and self.getThreshold() != 0.5:
            labels = (probs[:, 1] > self.getThreshold()).astype(np.int32)
        return labels, probs, np.asarray(raw)

    def transform(self, dataset: Any) -> Any:
        if isinstance(dataset, DataFrame):
            x = as_matrix(dataset.select(self.getFeaturesCol()))
            labels, probs, raw = self._predict_all(x)
            out = dataset.withColumn(self.getRawPredictionCol(), list(raw))
            out = out.withColumn(self.getProbabilityCol(), list(probs))
            return out.withColumn(self.getPredictionCol(), list(labels))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                if self.getFeaturesCol() in dataset.columns:
                    x = as_matrix(dataset[self.getFeaturesCol()].tolist())
                else:
                    cols = [c for c in dataset.columns if c != self.getLabelCol()]
                    x = dataset[cols].to_numpy(dtype=np.float64)
                labels, probs, raw = self._predict_all(x)
                out = dataset.copy()
                out[self.getRawPredictionCol()] = list(raw)
                out[self.getProbabilityCol()] = list(probs)
                out[self.getPredictionCol()] = labels
                return out
        except ImportError:  # pragma: no cover
            pass
        return self.predict(dataset)

    def evaluate(self, dataset: Any) -> dict:
        """Summary metrics: accuracy / error rate on a labeled dataset."""
        x, y = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        pred = self.predict(x)
        mask = jnp.ones(len(y))
        acc, err = classification_metrics(
            jnp.asarray(y.astype(np.int32)), jnp.asarray(pred.astype(np.int32)), mask
        )
        return {"accuracy": float(acc), "errorRate": float(err)}

    def _save_impl(self, path: str) -> None:
        save_metadata(
            self,
            path,
            class_name="org.apache.spark.ml.classification.LogisticRegressionModel",
            extra_metadata={"numClasses": self.numClasses, "numIter": self.numIter},
        )
        save_data(
            path,
            {
                "weights": ("matrix", self.weights),
                "intercepts": ("vector", self.intercepts),
            },
        )

    @classmethod
    def _load_impl(cls, path: str) -> "LogisticRegressionModel":
        metadata = load_metadata(path, expected_class="LogisticRegressionModel")
        data = load_data(path)
        model = cls(
            metadata["uid"],
            data["weights"],
            data["intercepts"],
            numClasses=metadata.get("numClasses", 2),
            numIter=metadata.get("numIter", 0),
        )
        get_and_set_params(model, metadata)
        return model
