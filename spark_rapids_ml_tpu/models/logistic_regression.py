"""LogisticRegression estimator/model — Spark ML surface, L-BFGS on the MXU.

Param surface mirrors ``org.apache.spark.ml.classification.LogisticRegression``:
``featuresCol``, ``labelCol``, ``predictionCol``, ``probabilityCol``,
``rawPredictionCol``, ``maxIter``, ``regParam``, ``elasticNetParam`` (0 ->
L2 via jitted L-BFGS; > 0 -> L1/elastic net via jitted FISTA, Spark's
OWL-QN analogue), ``tol``, ``fitIntercept``, ``standardization``,
``family`` ("auto" | "binomial" | "multinomial"), ``threshold``.
Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2); the whole optimization is one jitted program (ops.logistic),
mesh-shardable.

Model attributes follow Spark: binomial exposes ``coefficients`` (d,) and
``intercept``; multinomial exposes ``coefficientMatrix`` (numClasses, d) and
``interceptVector`` (numClasses,).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    as_matrix,
    extract_weights,
    is_device_array,
    is_streaming_source,
)
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.ingest import (
    matrix_like,
    prepare_labels,
    prepare_rows,
    validate_int_labels,
)
from spark_rapids_ml_tpu.core.lazy_state import LazyHostState
from spark_rapids_ml_tpu.core.params import Param, Params, toBoolean, toFloat, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_data,
    load_metadata,
    save_data,
    save_metadata,
)
from spark_rapids_ml_tpu.models.linear_regression import _extract_xy
from spark_rapids_ml_tpu.ops.logistic import (
    classification_metrics,
    fit_logistic,
    fit_logistic_elastic_net,
    fit_logistic_resumable,
    predict_logistic,
)
from spark_rapids_ml_tpu.core.serving import (
    note_device_cache,
    serve_blocks,
    serve_rows,
    stream_block_rows,
)
from spark_rapids_ml_tpu.utils.envknobs import env_choice
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


def _logistic_fused_knob() -> bool:
    """TPUML_LOGISTIC_FUSED, read in the model layer (outside jit) and
    plumbed into the solvers as a static arg."""
    return env_choice("TPUML_LOGISTIC_FUSED", ("0", "1"), "1") == "1"


def _forward_kernel(
    x, w, b, *, n_classes: int, threshold: float, precision: str = "highest"
):
    """Serving kernel: one forward pass -> (labels, probs, raw logits).
    The batch follows the weights' dtype (the fitted precision is the
    numerics contract; the cast fuses into the logits GEMM).
    ``precision`` is the resolved serving-family policy mode
    (ops/precision.py) — static, so it keys the AOT program cache."""
    labels, probs, raw = predict_logistic(
        x.astype(w.dtype), w, b, n_classes=n_classes, precision=precision
    )
    if w.shape[1] == 1 and threshold != 0.5:
        labels = (probs[:, 1] > threshold).astype(jnp.int32)
    return labels, probs, raw


def _select_labels(outs):
    """Transform-contract selection for the fuser: a pipeline ending in a
    classifier yields LABELS (``transform`` on a plain array returns
    ``predict``'s labels); probabilities and raw margins are downstream-
    dead, so selecting in-program lets XLA eliminate their writes."""
    labels, _probs, _raw = outs
    return labels


class _LogisticRegressionParams(Params):
    featuresCol = Param("_", "featuresCol", "features column name", toString)
    labelCol = Param("_", "labelCol", "label column name", toString)
    predictionCol = Param("_", "predictionCol", "prediction column name", toString)
    probabilityCol = Param("_", "probabilityCol", "class probabilities column", toString)
    rawPredictionCol = Param("_", "rawPredictionCol", "raw logits column", toString)
    maxIter = Param("_", "maxIter", "maximum L-BFGS iterations", toInt)
    regParam = Param("_", "regParam", "L2 regularization strength", toFloat)
    elasticNetParam = Param("_", "elasticNetParam", "L1/L2 mixing (0 = pure L2)", toFloat)
    tol = Param("_", "tol", "gradient-norm convergence tolerance", toFloat)
    fitIntercept = Param("_", "fitIntercept", "whether to fit an intercept", toBoolean)
    standardization = Param(
        "_", "standardization", "optimize in standardized feature space", toBoolean
    )
    family = Param("_", "family", "auto, binomial, or multinomial", toString)
    threshold = Param("_", "threshold", "binary decision threshold", toFloat)
    weightCol = Param("_", "weightCol", "per-row weight column name", toString)
    precision = Param(
        "_", "precision",
        "matmul precision for the X-sweep GEMMs (ops/precision.py): "
        "highest/f32 (reference-parity default) | high | bf16x3 (3-pass "
        "compensated split, max rel err <= 2e-4) | default/bf16 (1-pass). "
        "Unset, the TPUML_PRECISION[_LOGISTIC] knobs and committed "
        "autotune decisions apply (resolve_policy layering).",
        toString,
    )

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            maxIter=100,
            regParam=0.0,
            elasticNetParam=0.0,
            tol=1e-6,
            fitIntercept=True,
            standardization=True,
            family="auto",
            threshold=0.5,
            precision="highest",
        )

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)

    def getStandardization(self) -> bool:
        return self.getOrDefault(self.standardization)

    def getFamily(self) -> str:
        return self.getOrDefault(self.family)

    def getThreshold(self) -> float:
        return self.getOrDefault(self.threshold)

    def getPrecision(self) -> str:
        return self.getOrDefault(self.precision)

    def getWeightCol(self):
        return (
            self.getOrDefault(self.weightCol)
            if self.isDefined(self.weightCol)
            else None
        )


class LogisticRegression(_LogisticRegressionParams, Estimator, MLReadable):
    """``LogisticRegression().setRegParam(0.1).fit((X, y))``."""

    # Consumes device (X, y) pairs in place, so tuning loops may feed
    # device-resident fold slices (tuning._device_fold_prep).
    _device_foldable = True

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setFeaturesCol(self, value: str) -> "LogisticRegression":
        self.set(self.featuresCol, value)
        return self

    def setLabelCol(self, value: str) -> "LogisticRegression":
        self.set(self.labelCol, value)
        return self

    def setPredictionCol(self, value: str) -> "LogisticRegression":
        self.set(self.predictionCol, value)
        return self

    def setProbabilityCol(self, value: str) -> "LogisticRegression":
        self.set(self.probabilityCol, value)
        return self

    def setRawPredictionCol(self, value: str) -> "LogisticRegression":
        self.set(self.rawPredictionCol, value)
        return self

    def setMaxIter(self, value: int) -> "LogisticRegression":
        self.set(self.maxIter, value)
        return self

    def setRegParam(self, value: float) -> "LogisticRegression":
        if value < 0:
            raise ValueError(f"regParam must be >= 0, got {value}")
        self.set(self.regParam, value)
        return self

    def setElasticNetParam(self, value: float) -> "LogisticRegression":
        if not 0.0 <= value <= 1.0:
            raise ValueError(f"elasticNetParam must be in [0, 1], got {value}")
        self.set(self.elasticNetParam, value)
        return self

    def setTol(self, value: float) -> "LogisticRegression":
        self.set(self.tol, value)
        return self

    def setFitIntercept(self, value: bool) -> "LogisticRegression":
        self.set(self.fitIntercept, value)
        return self

    def setStandardization(self, value: bool) -> "LogisticRegression":
        self.set(self.standardization, value)
        return self

    def setFamily(self, value: str) -> "LogisticRegression":
        if value not in ("auto", "binomial", "multinomial"):
            raise ValueError(f"family must be auto/binomial/multinomial, got {value!r}")
        self.set(self.family, value)
        return self

    def setThreshold(self, value: float) -> "LogisticRegression":
        self.set(self.threshold, value)
        return self

    def setPrecision(self, value: str) -> "LogisticRegression":
        from spark_rapids_ml_tpu.ops.precision import validate_mode

        self.set(self.precision, validate_mode(value))
        return self

    def setWeightCol(self, value: str) -> "LogisticRegression":
        self.set(self.weightCol, value)
        return self

    def setMesh(self, mesh) -> "LogisticRegression":
        self.mesh = mesh
        return self

    _initial_weights = None  # (weights (d, c), intercepts (c,)) warm start
    _copy_attrs = ("_initial_weights",)

    def setInitialModel(self, value) -> "LogisticRegression":
        """Warm start the L-BFGS solve from an existing model's solution —
        resume an interrupted fit, or seed a regularization-path sweep
        (each grid cell starts from the previous optimum). Applies to the
        L-BFGS (L2 / unregularized) path."""
        w = np.asarray(value.weights, dtype=np.float64)
        b = np.asarray(value.intercepts, dtype=np.float64)
        if w.ndim != 2 or b.ndim != 1 or w.shape[1] != b.shape[0]:
            raise ValueError("initial model must carry (d, c) weights and (c,) intercepts")
        self._initial_weights = (w, b)
        return self

    def _fit(self, dataset: Any) -> "LogisticRegressionModel":
        if (
            isinstance(dataset, tuple)
            and len(dataset) == 2
            and is_streaming_source(dataset[0])
        ):
            return self._fit_streaming(dataset)
        x_in, y_in = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        w_host = extract_weights(dataset, self.getWeightCol())
        from spark_rapids_ml_tpu.core import membudget

        # Budgeted admission (core/membudget.py): an over-budget host
        # input reroutes to the SAME (reader, y) streaming fit an explicit
        # streaming source takes — bit-identical by construction — and a
        # device OOM mid-fit reclaims caches and takes the same exit.
        can_stream = (
            w_host is None
            and not (self.getElasticNetParam() > 0.0 and self.getRegParam() > 0.0)
            and self._initial_weights is None
        )
        guard = membudget.fit_memory_guard(
            "logistic", x_in, can_stream=can_stream,
            why_cannot_stream="the streaming path supports neither "
                              "weightCol, elastic net, nor warm starts",
            mesh=self.mesh, ledger_families=("logistic",),
        )
        if guard.degrade:
            return membudget.run_streaming_with_recovery(
                "logistic", lambda r: self._fit((r, y_in)), guard.matrix
            )
        fallback = (
            (lambda: membudget.run_streaming_with_recovery(
                "logistic", lambda r: self._fit((r, y_in)),
                membudget.host_matrix(x_in)))
            if can_stream and self.mesh is None else None
        )
        return membudget.run_fit_with_oom_recovery(
            "logistic", lambda: self._fit_in_memory(x_in, y_in, w_host), fallback
        )

    def _train_precision(self) -> str:
        """Resolve the fit-time GEMM policy (ops/precision.py): explicit
        ``setPrecision`` wins, then TPUML_PRECISION[_LOGISTIC], then a
        committed autotune decision; the default stays 'highest'."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        return resolve_policy(
            "logistic", requested, default=self.getPrecision()
        )

    def _fit_in_memory(self, x_in, y_in, w_host) -> "LogisticRegressionModel":
        # Device labels validate on device (two scalar readbacks — the
        # class count defines shapes, so a sync is inherent; what never
        # happens is an O(n) pull of the label vector).
        y_int, n_classes = validate_int_labels(y_in)
        import jax

        if self.mesh is not None and jax.process_count() > 1:
            # Gang deploy mode: each member counted classes from its LOCAL
            # labels, but n_classes is a trace-time shape — members must
            # agree globally or they trace different programs and deadlock
            # in the first collective.
            from spark_rapids_ml_tpu.parallel.distributed import (
                allgather_host_max,
            )

            n_classes = allgather_host_max(n_classes)
        family = self.getFamily()
        if family == "auto":
            family = "binomial" if n_classes <= 2 else "multinomial"
        if family == "binomial" and n_classes > 2:
            raise ValueError(f"binomial family with {n_classes} labels")
        n_classes = max(n_classes, 2)

        with TraceRange("logreg fit", TraceColor.YELLOW):
            # One funnel for every residence: device arrays fit in place
            # (VERDICT r3 #1), host data places once, dtype-preserving.
            xs, mask, n, d = prepare_rows(x_in, mesh=self.mesh, weights=w_host)
            dtype = xs.dtype
            ys = prepare_labels(
                y_int, int(xs.shape[0]), n_true=n, mesh=self.mesh, dtype=jnp.int32
            )
            use_multinomial = family == "multinomial"
            # Knob read OUTSIDE jit; the flag rides into the programs as a
            # static arg (fused one-pass loss+grad vs legacy two-pass AD).
            fused = _logistic_fused_knob()
            precision = self._train_precision()
            enet = self.getElasticNetParam()
            # regParam == 0 means zero effective penalty whatever enet says:
            # use the L-BFGS path (faster, and it applies the multinomial
            # identifiability pivot the proximal path has no need for).
            init_w = init_b = None
            if self._initial_weights is not None:
                w0, b0 = self._initial_weights
                c_expect = n_classes if (use_multinomial or n_classes > 2) else 1
                if w0.shape != (d, c_expect):
                    raise ValueError(
                        f"initial model weights {w0.shape} != expected "
                        f"({d}, {c_expect})"
                    )
                # Pad to any model-axis feature padding the mesh added.
                pad_d = xs.shape[1] - w0.shape[0]
                init_w = jnp.asarray(
                    np.pad(w0, ((0, pad_d), (0, 0))), dtype=dtype
                )
                init_b = jnp.asarray(b0, dtype=dtype)
            if enet == 0.0 or self.getRegParam() == 0.0:
                # Preemption tolerance: the TPUML_CHECKPOINT_* knobs route
                # the L-BFGS solve through the segmented driver (async
                # snapshots, mid-solve resume, bit-identical results).
                ckpt = self._fit_checkpointer("logistic.lbfgs", data=(xs, ys, mask))
                fit_fn = fit_logistic
                extra = {}
                if ckpt is not None:
                    fit_fn = fit_logistic_resumable
                    extra = {"checkpointer": ckpt, "mesh": self.mesh}
                result = fit_fn(
                    xs,
                    ys,
                    mask,
                    n_classes=n_classes,
                    reg_param=self.getRegParam(),
                    fit_intercept=self.getFitIntercept(),
                    standardization=self.getStandardization(),
                    max_iter=self.getMaxIter(),
                    tol=self.getTol(),
                    multinomial=use_multinomial,
                    init_w=init_w,
                    init_b=init_b,
                    fused=fused,
                    precision=precision,
                    **extra,
                )
            else:
                if self._initial_weights is not None:
                    raise ValueError(
                        "setInitialModel warm start applies to the L-BFGS "
                        "path (elasticNetParam 0 or regParam 0)"
                    )
                # L1/elastic net: FISTA (Spark reaches this via OWL-QN).
                # maxIter caps proximal iterations exactly as it caps
                # OWL-QN iterations in Spark — users of the slower-
                # converging proximal steps raise maxIter, preserving the
                # totalIterations <= maxIter invariant.
                result = fit_logistic_elastic_net(
                    xs,
                    ys,
                    mask,
                    n_classes=n_classes,
                    reg_param=self.getRegParam(),
                    elastic_net_param=enet,
                    fit_intercept=self.getFitIntercept(),
                    standardization=self.getStandardization(),
                    max_iter=self.getMaxIter(),
                    tol=self.getTol(),
                    multinomial=use_multinomial,
                    fused=fused,
                    precision=precision,
                )
        # Gang fits can hand back sharded results; replicate them so every
        # member's host reads see identical values.
        from spark_rapids_ml_tpu.parallel.distributed import replicate_for_host

        weights, intercepts = replicate_for_host(
            self.mesh, result.weights, result.intercepts
        )
        # Strip model-axis feature padding (device slice, stays async);
        # host float64 conversion happens lazily inside the model.
        model = LogisticRegressionModel(
            self.uid,
            weights[:d],
            intercepts,
            numClasses=n_classes,
            numIter=result.n_iter,
        )
        return self._copyValues(model)

    def _fit_streaming(self, dataset) -> "LogisticRegressionModel":
        """Re-iterable (X_stream, y) sources: multi-pass L-BFGS at
        O(block + d*c) memory — one stats pass (moments + label scan),
        then one data pass per objective evaluation
        (:func:`ops.logistic.fit_logistic_streaming`). VERDICT r3 #6."""
        from spark_rapids_ml_tpu.core.data import is_reiterable_stream
        from spark_rapids_ml_tpu.models.linear_regression import _streaming_blocks
        from spark_rapids_ml_tpu.ops.logistic import (
            fit_logistic_streaming,
            streaming_label_feature_stats,
        )

        if not is_reiterable_stream(dataset[0]):
            raise ValueError(
                "LogisticRegression is multi-pass: a streaming fit needs a "
                "RE-ITERABLE source (a zero-arg iterator factory or a block "
                "reader with .iter_blocks()), not a one-shot generator"
            )
        if self.mesh is not None:
            raise ValueError(
                "streaming LogisticRegression is single-device; pass host "
                "partitions for a mesh fit"
            )
        if self.getWeightCol() is not None:
            raise TypeError(
                "weightCol requires a dataset with named columns; streaming "
                "block sources carry no columns"
            )
        if self.getElasticNetParam() > 0.0 and self.getRegParam() > 0.0:
            raise ValueError(
                "streaming elastic net is not supported (FISTA needs the "
                "in-memory design); use elasticNetParam=0 or materialize"
            )
        if self._initial_weights is not None:
            raise ValueError(
                "setInitialModel warm start is not supported for streaming "
                "fits yet"
            )

        n, mean, sigma, y_max, y_int_ok = streaming_label_feature_stats(
            _streaming_blocks(dataset)
        )
        if not y_int_ok:
            raise ValueError("labels must be integers in [0, numClasses)")
        n_classes = y_max + 1
        family = self.getFamily()
        if family == "auto":
            family = "binomial" if n_classes <= 2 else "multinomial"
        if family == "binomial" and n_classes > 2:
            raise ValueError(f"binomial family with {n_classes} labels")
        n_classes = max(n_classes, 2)

        with TraceRange("logreg stream fit", TraceColor.YELLOW):
            result = fit_logistic_streaming(
                lambda: _streaming_blocks(dataset),
                n_classes,
                n=n,
                mean=mean,
                sigma=sigma,
                reg_param=self.getRegParam(),
                fit_intercept=self.getFitIntercept(),
                standardization=self.getStandardization(),
                max_iter=self.getMaxIter(),
                tol=self.getTol(),
                multinomial=family == "multinomial",
                fused=_logistic_fused_knob(),
                precision=self._train_precision(),
            )
        model = LogisticRegressionModel(
            self.uid,
            np.asarray(result.weights, dtype=np.float64),
            np.asarray(result.intercepts, dtype=np.float64),
            numClasses=n_classes,
            numIter=int(result.n_iter),
        )
        return self._copyValues(model)


class LogisticRegressionModel(_LogisticRegressionParams, Model, LazyHostState):
    """Fitted model. ``weights``: (d, 1) binomial sigmoid column or (d, c)
    softmax matrix; ``intercepts``: (1,) or (c,).

    Fitted state may be host numpy OR live jax.Arrays from a device-
    resident fit; host float64 views convert lazily and pickling
    materializes host state (core/lazy_state.LazyHostState)."""

    _lazy_host_fields = {
        "_w_raw": ("_w_np", np.float64),
        "_b_raw": ("_b_np", np.float64),
    }
    _pickle_clear = ("_wb_dev",)

    def __init__(
        self,
        uid: Optional[str] = None,
        weights: Optional[np.ndarray] = None,
        intercepts: Optional[np.ndarray] = None,
        numClasses: int = 2,
        numIter: int = 0,
    ):
        super().__init__(uid)
        self._w_raw = weights
        self._b_raw = intercepts
        self._w_np: Optional[np.ndarray] = None
        self._b_np: Optional[np.ndarray] = None
        self._wb_dev = None
        self.numClasses = numClasses
        self._iter_raw = numIter

    def __getstate__(self):
        state = super().__getstate__()
        state["_iter_raw"] = self.numIter
        return state

    @property
    def weights(self) -> Optional[np.ndarray]:
        return self._lazy_host_view("_w_raw")

    @property
    def intercepts(self) -> Optional[np.ndarray]:
        return self._lazy_host_view("_b_raw")

    @property
    def numIter(self) -> int:
        if not isinstance(self._iter_raw, int):
            self._iter_raw = int(self._iter_raw)
        return self._iter_raw

    def setFeaturesCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.featuresCol, value)
        return self

    def setPredictionCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.predictionCol, value)
        return self

    def setProbabilityCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.probabilityCol, value)
        return self

    def setRawPredictionCol(self, value: str) -> "LogisticRegressionModel":
        self.set(self.rawPredictionCol, value)
        return self

    def setThreshold(self, value: float) -> "LogisticRegressionModel":
        self.set(self.threshold, value)
        return self

    def copy(self, extra=None) -> "LogisticRegressionModel":
        that = LogisticRegressionModel(
            self.uid, self._w_raw, self._b_raw, self.numClasses, self._iter_raw
        )
        return self._copyValues(that, extra)

    # --- Spark-style accessors ---

    @property
    def coefficients(self) -> np.ndarray:
        """Binomial coefficient vector (d,). Raises for multinomial (Spark
        throws the same way)."""
        if self.weights.shape[1] != 1:
            raise AttributeError("multinomial model: use coefficientMatrix")
        return self.weights[:, 0]

    @property
    def intercept(self) -> float:
        if self.intercepts.shape[0] != 1:
            raise AttributeError("multinomial model: use interceptVector")
        return float(self.intercepts[0])

    @property
    def coefficientMatrix(self) -> np.ndarray:
        """Spark's orientation: (1, d) for binomial, (numClasses, d) for
        multinomial."""
        return self.weights.T

    @property
    def interceptVector(self) -> np.ndarray:
        return self.intercepts.copy()

    def predict(self, x) -> np.ndarray:
        labels, _, _ = self._predict_all(x)
        return labels

    def predictProbability(self, x) -> np.ndarray:
        _, probs, _ = self._predict_all(x)
        return probs

    def predictRaw(self, x) -> np.ndarray:
        """Raw margins (Spark's rawPrediction): [-z, z] for binomial,
        the logits for multinomial — NOT probabilities."""
        _, _, raw = self._predict_all(x)
        return raw

    def _predict_all(self, x):
        """One forward pass through the shape-bucketed serving program
        cache; binomial labels honor the threshold param (applied INSIDE
        the program so a threshold change is a new program, not a per-call
        epilogue). Device queries keep everything on device; host queries
        keep the numpy contract. Large host batches stream block by
        block through the double-buffered path (H2D of block k+1
        overlaps the forward GEMM of block k)."""
        w, b = self._wb_serving()
        static = {
            "n_classes": self.numClasses,
            "threshold": float(self.getThreshold()),
            "precision": self._serving_precision(),
        }
        x = matrix_like(x)
        if not is_device_array(x):
            xh = np.asarray(x)
            if xh.ndim == 2 and xh.shape[0] > stream_block_rows():
                return serve_blocks(
                    _forward_kernel,
                    xh,
                    (w, b),
                    static=static,
                    name="logreg.predict",
                )
        return serve_rows(
            _forward_kernel,
            x,
            (w, b),
            static=static,
            name="logreg.predict",
        )

    def _serving_precision(self) -> str:
        """The serving-family policy mode (ops/precision.py): an explicit
        estimator ``setPrecision`` survives into the model and wins;
        otherwise the TPUML_PRECISION[_SERVING] knobs and committed
        autotune decisions apply. Part of the static dict, hence of the
        AOT/program cache key."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        return resolve_policy("serving", requested)

    def _wb_serving(self):
        """Weights/intercepts as ONE device-resident pair reused across
        predict calls (device-resident fits already hold them there)."""
        if self._wb_dev is None:
            w = self._w_raw if is_device_array(self._w_raw) else jnp.asarray(self.weights)
            b = self._b_raw if is_device_array(self._b_raw) else jnp.asarray(self.intercepts)
            self._wb_dev = (w, b.astype(w.dtype))
            note_device_cache(self)
        return self._wb_dev

    def serving_signature(self):
        """The online-serving contract: the forward kernel, the
        device-resident (weights, intercepts) pair, and the
        (labels, probabilities, raw margins) output specs."""
        import jax

        from spark_rapids_ml_tpu.serving.signature import ServingSignature

        if self._w_raw is None:
            raise RuntimeError("model has no weights")
        w, b = self._wb_serving()
        n_out = max(2, self.numClasses)
        return ServingSignature(
            kernel=_forward_kernel,
            weights=(w, b),
            static={
                "n_classes": self.numClasses,
                "threshold": float(self.getThreshold()),
                "precision": self._serving_precision(),
            },
            name="logreg.predict",
            n_features=int(w.shape[0]),
            output_spec=lambda n, dtype: (
                jax.ShapeDtypeStruct((n,), np.int32),
                jax.ShapeDtypeStruct((n, n_out), w.dtype),
                jax.ShapeDtypeStruct((n, n_out), w.dtype),
            ),
            select=_select_labels,
        )

    def transform(self, dataset: Any) -> Any:
        if isinstance(dataset, DataFrame):
            x = as_matrix(dataset.select(self.getFeaturesCol()))
            labels, probs, raw = self._predict_all(x)
            out = dataset.withColumn(self.getRawPredictionCol(), list(raw))
            out = out.withColumn(self.getProbabilityCol(), list(probs))
            return out.withColumn(self.getPredictionCol(), list(labels))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                if self.getFeaturesCol() in dataset.columns:
                    x = as_matrix(dataset[self.getFeaturesCol()].tolist())
                else:
                    cols = [c for c in dataset.columns if c != self.getLabelCol()]
                    x = dataset[cols].to_numpy(dtype=np.float64)
                labels, probs, raw = self._predict_all(x)
                out = dataset.copy()
                out[self.getRawPredictionCol()] = list(raw)
                out[self.getProbabilityCol()] = list(probs)
                out[self.getPredictionCol()] = labels
                return out
        except ImportError:  # pragma: no cover
            pass
        return self.predict(dataset)

    def evaluate(self, dataset: Any) -> dict:
        """Summary metrics: accuracy / error rate on a labeled dataset."""
        x, y = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        pred = self.predict(x)
        mask = jnp.ones(len(y))
        acc, err = classification_metrics(
            jnp.asarray(y.astype(np.int32)), jnp.asarray(pred.astype(np.int32)), mask
        )
        return {"accuracy": float(acc), "errorRate": float(err)}

    def _save_impl(self, path: str) -> None:
        save_metadata(
            self,
            path,
            class_name="org.apache.spark.ml.classification.LogisticRegressionModel",
            extra_metadata={"numClasses": self.numClasses, "numIter": self.numIter},
        )
        # Spark LogisticRegressionModel's exact data row (its Data case
        # class): numClasses, numFeatures, interceptVector,
        # coefficientMatrix ((1, d) binomial / (C, d) multinomial),
        # isMultinomial — byte-compatible with upstream readers
        # (VERDICT r4 #6; the SURVEY §3.4 discipline).
        save_data(
            path,
            {
                "numClasses": ("scalar", int(self.numClasses)),
                "numFeatures": ("scalar", int(self.weights.shape[0])),
                "interceptVector": ("vector", self.intercepts),
                "coefficientMatrix": ("matrix", self.coefficientMatrix),
                "isMultinomial": ("scalar", bool(self.intercepts.shape[0] > 1)),
            },
        )

    @classmethod
    def _load_impl(cls, path: str) -> "LogisticRegressionModel":
        metadata = load_metadata(path, expected_class="LogisticRegressionModel")
        data = load_data(path)
        if "coefficientMatrix" in data:
            weights = np.asarray(data["coefficientMatrix"]).T  # (d, 1|C)
            intercepts = np.asarray(data["interceptVector"])
            n_classes = int(data.get("numClasses", metadata.get("numClasses", 2)))
        else:  # directories written before the Spark-schema alignment (r5)
            weights = data["weights"]
            intercepts = data["intercepts"]
            n_classes = metadata.get("numClasses", 2)
        model = cls(
            metadata["uid"],
            weights,
            intercepts,
            numClasses=n_classes,
            numIter=metadata.get("numIter", 0),
        )
        get_and_set_params(model, metadata)
        return model
