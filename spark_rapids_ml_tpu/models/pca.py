"""PCA estimator/model — the user-facing L1/L2 layer.

Reference: ``com.nvidia.spark.ml.feature.PCA`` (PCA.scala:27, a thin rename)
over ``RapidsPCA`` / ``RapidsPCAModel`` (RapidsPCA.scala). Param surface kept
name-for-name (RapidsPCA.scala:30-106): ``k``, ``inputCol``, ``outputCol``,
``meanCentering`` (default True, :36-37), ``useGemm`` (default True, :47-49),
``useCuSolverSVD`` (default True, :58-59 — here it routes to the XLA
eigensolver; name retained for drop-in compatibility), ``gpuId`` (default −1,
:70-71 — here the TPU chip ordinal).

Differences by design (SURVEY.md §7 "beyond-parity"):
  - ``transform`` is the *batched accelerated* projection (one AᵀB GEMM per
    partition) — the path the reference disabled as too slow
    (RapidsPCA.scala:172-185). A per-row host path is kept for tiny inputs.
  - both covariance paths normalize by (numRows − 1) (quirk §7.5 fixed).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    as_matrix,
    as_partitions,
    extract_column,
    num_features,
)
from spark_rapids_ml_tpu.core.estimator import Estimator, HasInputCol, HasOutputCol, Model
from spark_rapids_ml_tpu.core.lazy_state import LazyHostState
from spark_rapids_ml_tpu.core.params import Param, gt, toBoolean, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_data,
    load_metadata,
    save_data,
    save_metadata,
)
from spark_rapids_ml_tpu.core.serving import (
    note_device_cache,
    serve_rows,
    serve_stream,
)
from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix
from spark_rapids_ml_tpu.ops.linalg import project_rows
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


def _project_kernel(x, pc, *, precision: str = "highest"):
    """Serving kernel: rows onto the principal subspace. The cast follows
    the device-transform convention (components follow the batch dtype)
    and fuses into the projection GEMM."""
    return project_rows(x, pc.astype(x.dtype), precision=precision)


class _PCAParams(HasInputCol, HasOutputCol):
    """RapidsPCAParams equivalent (RapidsPCA.scala:30-75)."""

    k = Param("_", "k", "number of principal components", lambda v: gt(0)(toInt(v)))
    meanCentering = Param("_", "meanCentering", "whether to center data before covariance", toBoolean)
    useGemm = Param("_", "useGemm", "use dense fused GEMM covariance (else packed spr layout)", toBoolean)
    useCuSolverSVD = Param(
        "_", "useCuSolverSVD", "use the accelerated (XLA) eigensolver instead of host SVD", toBoolean
    )
    gpuId = Param("_", "gpuId", "accelerator chip ordinal; -1 = runtime-assigned", toInt)
    solver = Param(
        "_", "solver", "auto | covariance | randomized (wide-feature sketch)", toString
    )
    precision = Param(
        "_",
        "precision",
        "auto | default | high | highest | dd (double-float fp64 emulation)",
        toString,
    )
    covarianceBackend = Param(
        "_",
        "covarianceBackend",
        "xla (fused, default) | pallas (VMEM-resident streaming kernel)",
        toString,
    )
    eigenSolver = Param(
        "_",
        "eigenSolver",
        "auto (self-selecting, default) | full (exact eigh) | "
        "topk (subspace iteration, k << d)",
        toString,
    )
    eigenIters = Param(
        "_",
        "eigenIters",
        "subspace iterations for eigenSolver='topk' (raise for slowly "
        "decaying spectra: subspace error ~ (lambda_{k+1}/lambda_k)^iters)",
        toInt,
    )

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            meanCentering=True, useGemm=True, useCuSolverSVD=True, gpuId=-1,
            solver="auto", precision="auto", covarianceBackend="xla",
            eigenSolver="auto", eigenIters=8,
        )

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getMeanCentering(self) -> bool:
        return self.getOrDefault(self.meanCentering)

    def getUseGemm(self) -> bool:
        return self.getOrDefault(self.useGemm)

    def getUseCuSolverSVD(self) -> bool:
        return self.getOrDefault(self.useCuSolverSVD)

    def getGpuId(self) -> int:
        return self.getOrDefault(self.gpuId)

    def getSolver(self) -> str:
        return self.getOrDefault(self.solver)

    def getPrecision(self) -> str:
        return self.getOrDefault(self.precision)

    def getCovarianceBackend(self) -> str:
        return self.getOrDefault(self.covarianceBackend)

    def getEigenSolver(self) -> str:
        return self.getOrDefault(self.eigenSolver)

    def getEigenIters(self) -> int:
        return self.getOrDefault(self.eigenIters)


class PCA(_PCAParams, Estimator, MLReadable):
    """PCA estimator. ``PCA().setK(3).setInputCol("features").fit(df)``."""

    # Consumes device arrays in place, so tuning loops may feed
    # device-resident fold slices (tuning._device_fold_prep).
    _device_foldable = True

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    # chainable setters (RapidsPCA.scala:80-106)
    def setK(self, value: int) -> "PCA":
        self.set(self.k, value)
        return self

    def setMeanCentering(self, value: bool) -> "PCA":
        self.set(self.meanCentering, value)
        return self

    def setUseGemm(self, value: bool) -> "PCA":
        self.set(self.useGemm, value)
        return self

    def setUseCuSolverSVD(self, value: bool) -> "PCA":
        self.set(self.useCuSolverSVD, value)
        return self

    def setGpuId(self, value: int) -> "PCA":
        self.set(self.gpuId, value)
        return self

    def setMesh(self, mesh) -> "PCA":
        self.mesh = mesh
        return self

    def setSolver(self, value: str) -> "PCA":
        if value not in ("auto", "covariance", "randomized"):
            raise ValueError(
                f"solver must be auto/covariance/randomized, got {value!r}"
            )
        self.set(self.solver, value)
        return self

    def setPrecision(self, value: str) -> "PCA":
        """Matmul precision for the covariance path. ``"dd"`` emulates fp64
        with double-float MXU GEMMs (ops.doubledouble) — the reference's
        ``double[]`` numerics bar (JniRAPIDSML.java:64-69) on fp32-only
        hardware; ``"auto"`` selects it when fitting float64 input without
        x64 support."""
        from spark_rapids_ml_tpu.ops.linalg import validate_precision

        self.set(self.precision, validate_precision(value))
        return self

    def setEigenSolver(self, value: str) -> "PCA":
        """``"auto"`` (default) is self-selecting: subspace iteration that
        stops when its captured-variance objective stagnates and promotes
        itself to the full eigensolver when it runs out of iterations
        unconverged (ops.eigh.eigh_auto — the runtime check that replaces
        a static solver choice). ``"topk"`` forces subspace iteration +
        Rayleigh-Ritz (O(d^2 k) MXU matmuls instead of the full O(d^3)
        eigensolve): the right explicit choice when k << d and the
        spectrum decays; explained-variance ratios stay exact
        (trace-normalized). Convergence depends on the eigengap: subspace
        error shrinks like (lambda_{k+1}/lambda_k)^iters, so raise
        ``eigenIters`` (default 8) for slowly decaying spectra. ``"full"``
        is the reference-parity exact eigh (calSVD's eigDC,
        rapidsml_jni.cu:302-356)."""
        if value not in ("auto", "full", "topk"):
            raise ValueError(f"eigenSolver must be auto|full|topk, got {value!r}")
        self.set(self.eigenSolver, value)
        return self

    def setEigenIters(self, value: int) -> "PCA":
        """Iteration budget for the subspace eigensolvers. ``"topk"`` runs
        exactly this many; ``"auto"`` treats it as a CAP on an
        early-exiting loop and enforces a quality floor of
        ``ops.eigh.AUTO_MIN_ITERS`` (12) — below that the accept/promote
        check cannot separate converged from degenerate."""
        if value < 1:
            raise ValueError(f"eigenIters must be >= 1, got {value}")
        self.set(self.eigenIters, value)
        return self

    def setCovarianceBackend(self, value: str) -> "PCA":
        """Kernel backend for the covariance GEMM. Measured on v5e
        (BASELINE.md): "xla" (whole-array fusion) is fastest when the
        dataset fits HBM; "pallas" fuses centering + accumulation in VMEM
        and beats the XLA scan path when row-blocking is required."""
        if value not in ("xla", "pallas"):
            raise ValueError(f"covarianceBackend must be xla|pallas, got {value!r}")
        self.set(self.covarianceBackend, value)
        return self

    # Above this many features, "auto" switches to the randomized sketch:
    # the (d, d) covariance + full eigh grow as d^2 / d^3 while the sketch
    # stays O(n d l) with l = k + oversample.
    _RANDOMIZED_AUTO_DIM = 4096

    def _fit(self, dataset: Any) -> "PCAModel":
        """RapidsPCA.fit (RapidsPCA.scala:111-125)."""
        from spark_rapids_ml_tpu.core import membudget
        from spark_rapids_ml_tpu.core.data import is_streaming_source

        rows = extract_column(dataset, self.getInputCol())
        # Budgeted admission (core/membudget.py): an over-budget host
        # input re-enters this fit as a first-class block reader — the
        # SAME streaming moments/sketch path an explicit reader takes,
        # bit-identical by construction — and a device OOM mid-fit
        # reclaims caches and takes the same exit.
        can_stream = self.getCovarianceBackend() != "pallas"
        guard = membudget.fit_memory_guard(
            "pca", rows, can_stream=can_stream,
            why_cannot_stream="covarianceBackend='pallas' needs the "
                              "materialized single-device path",
            mesh=self.mesh, ledger_families=("pca",),
        )
        if guard.degrade:
            return membudget.run_streaming_with_recovery(
                "pca", self._fit, guard.matrix
            )
        fallback = (
            (lambda: membudget.run_streaming_with_recovery(
                "pca", self._fit, membudget.host_matrix(rows)))
            if can_stream and self.mesh is None
            and not is_streaming_source(rows) else None
        )
        return membudget.run_fit_with_oom_recovery(
            "pca", lambda: self._fit_in_memory(rows, dataset), fallback
        )

    def _fit_in_memory(self, rows: Any, dataset: Any) -> "PCAModel":
        """Solver routing + fit for an ADMITTED input: in-memory host or
        device data, or any streaming source (which the admission gate
        waves through untouched)."""
        from spark_rapids_ml_tpu.core.data import infer_input_dtype, is_streaming_source

        import jax

        from spark_rapids_ml_tpu.core.data import is_reiterable_stream

        solver = self.getSolver()
        streaming = is_streaming_source(rows)
        if solver == "randomized" and streaming and not is_reiterable_stream(rows):
            raise ValueError(
                "the randomized solver makes multiple passes; a one-shot "
                "generator cannot be re-read — pass an iterator factory "
                "(zero-arg callable) or a block reader (iter_blocks), or "
                "use solver='covariance' (one-pass)"
            )
        if solver == "randomized" and streaming and self.mesh is not None:
            # An explicit mesh must never be silently dropped: the
            # streaming sketch is single-device.
            raise ValueError(
                "the streaming randomized solver is single-device; unset "
                "the mesh, materialize the input (mesh-sharded sketch), or "
                "use solver='covariance' (streamed mesh covariance)"
            )
        if solver == "randomized" and jax.process_count() > 1:
            raise ValueError(
                "the randomized solver has no multi-process path; use "
                "solver='covariance' (per-executor streaming + moment merge)"
            )
        if solver == "randomized" and self.getPrecision() == "dd":
            raise ValueError(
                "the randomized solver has no dd path; use "
                "solver='covariance' with precision='dd'"
            )
        if self.getCovarianceBackend() == "pallas" and (
            self.mesh is not None
            or streaming
            or not self.getUseGemm()
            or solver == "randomized"
        ):
            raise ValueError(
                "covarianceBackend='pallas' applies to the single-device "
                "materialized GEMM covariance path (no mesh, no streaming "
                "source, useGemm=True, solver != 'randomized')"
            )
        # Resolve "auto" against the RAW input dtype (before densification
        # coerces to float64) so only genuinely-fp64 sources route to dd —
        # RowMatrix.resolve is the single home of this policy.
        requested_prec = self.getPrecision()
        # Probe the container extract_column did NOT already coerce: for a
        # pandas frame with no inputCol, extract_column densified to
        # float64, so the probe must look at the original frame.
        probe_source = rows
        if requested_prec == "auto" and self.getInputCol() is None:
            try:
                import pandas as pd

                if isinstance(dataset, pd.DataFrame):
                    probe_source = dataset
            except ImportError:  # pragma: no cover
                pass
        input_dtype = (
            infer_input_dtype(probe_source) if requested_prec == "auto" else None
        )
        # Mixed-precision policy layering (ops/precision.py): explicit
        # setPrecision > TPUML_PRECISION[_PCA] knobs > committed autotune
        # decision > the param default. fp64 input keeps its pre-policy
        # "auto" dd routing — the tuner never displaces fp64 emulation.
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        explicit = self.getPrecision() if self.isSet(self.precision) else None
        wants_f64 = input_dtype is not None and np.dtype(input_dtype) == np.float64
        if explicit is None and wants_f64:
            explicit = "auto"
        requested_prec = resolve_policy("pca", explicit, default=requested_prec)
        resolved_prec = RowMatrix.resolve(
            requested_prec,
            mesh=self.mesh,
            # Only "auto" needs the raw-dtype probe.
            input_dtype=input_dtype,
            backend=self.getCovarianceBackend(),
        )
        # 'auto' peeks at the first partition/block only — the covariance
        # path streams partitions, so routing must not force a densify.
        # An auto-resolved dd forces the covariance path (the sketch is
        # fp32-only), same as explicit precision='dd'. Wide-feature auto
        # routing covers materialized, mesh-sharded, and RE-ITERABLE
        # streaming inputs (one-shot generators cannot be multi-passed —
        # they keep the one-pass covariance path at any width).
        if solver == "randomized":
            return self._fit_randomized(rows)
        if (
            solver == "auto"
            and jax.process_count() == 1
            and resolved_prec != "dd"
            and self.getCovarianceBackend() != "pallas"  # explicit kernel choice
        ):
            from spark_rapids_ml_tpu.core.data import peek_stream_width

            if streaming:
                # mesh + stream keeps the streamed mesh covariance (the
                # streaming sketch is single-device — see the explicit-
                # solver guard above).
                wide = (
                    self.mesh is None
                    and is_reiterable_stream(rows)
                    and peek_stream_width(rows) >= self._RANDOMIZED_AUTO_DIM
                )
            else:
                wide = num_features(rows) >= self._RANDOMIZED_AUTO_DIM
                if wide and self.mesh is not None:
                    # auto must pick a WORKING path: the sketch does not
                    # shard the model axis, so a 2-D mesh whose model
                    # axis would pad the features keeps the mesh
                    # covariance (explicit solver='randomized' raises
                    # loudly instead).
                    from spark_rapids_ml_tpu.parallel.mesh import model_axis_size

                    mp = model_axis_size(self.mesh)
                    wide = num_features(rows) % mp == 0
            if wide:
                return self._fit_randomized(rows)
        mat = RowMatrix(
            rows,
            mean_centering=self.getMeanCentering(),
            use_gemm=self.getUseGemm(),
            use_accel_svd=self.getUseCuSolverSVD(),
            device_id=self.getGpuId(),
            mesh=self.mesh,
            precision=resolved_prec,
            backend=self.getCovarianceBackend(),
            eigen_solver=self.getEigenSolver(),
            eigen_iters=self.getEigenIters(),
        )
        pc, explained = mat.compute_principal_components_and_explained_variance(self.getK())
        # Device-resident fits return device arrays; PCAModel converts to
        # host float64 LAZILY, so a device-input fit never pays a host
        # transfer the caller didn't ask for (the fit stays fully async
        # until someone reads the model).
        model = PCAModel(self.uid, pc, explained)
        return self._copyValues(model)

    def _sketch_precision(self) -> str:
        """Policy mode for the randomized-sketch GEMMs (ops/precision.py).
        The sketch is fp32-only, so 'auto' resolves 'highest' here
        (explicit 'dd' was rejected before routing)."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        mode = resolve_policy("pca", requested, default="highest")
        return "highest" if mode in ("auto", "dd") else mode

    def _fit_randomized(self, rows) -> "PCAModel":
        """Wide-feature path: subspace sketch, no (d, d) covariance.

        Covers every input mode (VERDICT r2 #6): device arrays in place;
        host data on one chip; host partitions over a MESH (row-sharded
        with a padding mask — the sketch GEMMs shard like the covariance,
        one psum per rmatmul, no (d, d) on any device); and re-iterable
        block streams at O(d·l + block) memory (randomized_pca_streaming).
        """
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.core.data import (
            is_device_array,
            is_streaming_source,
        )
        from spark_rapids_ml_tpu.ops.randomized import (
            randomized_pca,
            randomized_pca_streaming,
        )

        k = self.getK()
        prec = self._sketch_precision()
        if is_streaming_source(rows):
            from spark_rapids_ml_tpu.core.data import iter_stream_blocks

            gpu_id = self.getGpuId()
            comps, ratio, _, _ = randomized_pca_streaming(
                lambda: iter_stream_blocks(rows),
                k,
                jax.random.key(0),
                center=self.getMeanCentering(),
                precision=prec,
                device=jax.local_devices()[gpu_id] if gpu_id >= 0 else None,
            )
            return self._copyValues(PCAModel(self.uid, comps, ratio))
        mask = None
        n_true = None
        if is_device_array(rows):
            # Already resident: sketch in place, stay async (lazy model).
            n, d = rows.shape
            if not 1 <= k <= min(n, d):
                raise ValueError(f"k must be in [1, {min(n, d)}], got {k}")
            x = rows
            if self.mesh is not None:
                # An explicit mesh must never be silently dropped (the
                # stance of RowMatrix._device_array_on_mesh): shard onto
                # the mesh so the sketch GEMMs run under GSPMD. Same
                # constraint as the host-partitions branch below: the
                # sketch cannot PAD the model axis, so features must
                # divide it exactly (mp=1 always does).
                from spark_rapids_ml_tpu.parallel.mesh import (
                    device_array_rows_on_mesh,
                    model_axis_size,
                )

                mp = model_axis_size(self.mesh)
                if d % mp != 0:
                    raise ValueError(
                        "the randomized solver does not shard the model "
                        f"axis (features {d} would pad to a multiple of "
                        f"{mp}); use a (dp, 1) mesh or solver='covariance'"
                    )
                x = device_array_rows_on_mesh(
                    x, self.mesh, shard_features=mp > 1
                )
        elif self.mesh is not None:
            from spark_rapids_ml_tpu.parallel.mesh import (
                shard_rows_from_partitions,
            )

            parts = as_partitions(rows)
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            if jax.process_count() > 1:
                # Gang deploy mode: these partitions are one member's LOCAL
                # rows — assemble the global sketch input through the
                # process-local funnel (same masked-padding semantics).
                from spark_rapids_ml_tpu.parallel.distributed import (
                    shard_rows_process_local,
                )

                x, mask, n_true, d = shard_rows_process_local(
                    parts, self.mesh, dtype=np.dtype(dtype)
                )
            else:
                x, mask, n_true = shard_rows_from_partitions(
                    parts, self.mesh, dtype=np.dtype(dtype)
                )
                d = parts[0].shape[1]
            if not 1 <= k <= min(n_true, d):
                raise ValueError(f"k must be in [1, {min(n_true, d)}], got {k}")
            if x.shape[1] != d:
                raise ValueError(
                    "the randomized solver does not shard the model axis "
                    f"(features {d} pad to {x.shape[1]}); use a (dp, 1) "
                    "mesh or solver='covariance'"
                )
        else:
            x_host = as_matrix(rows)
            n, d = x_host.shape
            if not 1 <= k <= min(n, d):
                raise ValueError(f"k must be in [1, {min(n, d)}], got {k}")
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            # Honor the chip-ordinal param the way the covariance path does
            # (RowMatrix._device); the sketch SEED stays fixed so the fitted
            # model never depends on placement.
            gpu_id = self.getGpuId()
            device = (
                jax.local_devices()[gpu_id]
                if gpu_id >= 0
                else jax.local_devices()[0]
            )
            # Guarded placement: the whole-dataset upload goes through the
            # ingest.device_put chokepoint (fault point, OOM retry + cache
            # reclaim) instead of a bare device_put.
            from spark_rapids_ml_tpu.core.ingest import place_array

            x = place_array(x_host, dtype=dtype, device=device)
        comps, ratio, _ = randomized_pca(
            x,
            k,
            jax.random.key(0),
            center=self.getMeanCentering(),
            mask=mask,
            n_true=n_true,
            precision=prec,
        )
        # Gang fits can hand back sharded results; the model's lazy host
        # pulls need them fully replicated (no-op otherwise).
        from spark_rapids_ml_tpu.parallel.distributed import replicate_for_host

        comps, ratio = replicate_for_host(self.mesh, comps, ratio)
        model = PCAModel(self.uid, comps, ratio)
        return self._copyValues(model)

class PCAModel(_PCAParams, Model, LazyHostState):
    """Fitted PCA model: principal components (d, k) + explained variance (k,).

    Reference: RapidsPCAModel (RapidsPCA.scala:146-205).
    """

    def __init__(
        self,
        uid: Optional[str] = None,
        pc: Optional[np.ndarray] = None,
        explainedVariance: Optional[np.ndarray] = None,
    ):
        super().__init__(uid)
        # Raw fitted state may be host numpy OR a jax.Array from a
        # device-resident fit; the public `pc`/`explainedVariance` host
        # float64 views convert lazily (and cache) so a device fit stays
        # async until the model is actually read. Pickling materializes
        # host state (core/lazy_state.LazyHostState).
        self._pc_raw = pc
        self._ev_raw = explainedVariance
        self._pc_np: Optional[np.ndarray] = None
        self._ev_np: Optional[np.ndarray] = None
        self._pc_dev_cache: dict = {}

    _lazy_host_fields = {
        "_pc_raw": ("_pc_np", np.float64),
        "_ev_raw": ("_ev_np", np.float64),
    }
    _pickle_clear = ("_pc_dev_cache",)
    _pickle_clear_values = {"_pc_dev_cache": {}}

    @property
    def pc(self) -> Optional[np.ndarray]:
        """Principal components (d, k) as host float64 (Spark's
        DenseMatrix surface, RapidsPCA.scala:146-150)."""
        return self._lazy_host_view("_pc_raw")

    @property
    def explainedVariance(self) -> Optional[np.ndarray]:
        """Explained-variance ratios (k,) as host float64."""
        return self._lazy_host_view("_ev_raw")

    def setInputCol(self, value: str) -> "PCAModel":
        self.set(self.inputCol, value)
        return self

    def setOutputCol(self, value: str) -> "PCAModel":
        self.set(self.outputCol, value)
        return self

    def copy(self, extra=None) -> "PCAModel":
        """Model.copy preserves fitted state (Spark's Model.copy contract)."""
        that = PCAModel(self.uid, self._pc_raw, self._ev_raw)
        return self._copyValues(that, extra)

    def transform(self, dataset: Any) -> Any:
        """Project rows onto the principal subspace: out = X · pc.

        The accelerated batched path (AᵀB GEMM per partition) — live here,
        disabled in the reference (RapidsPCA.scala:172-185). Returns the same
        container family as the input: DataFrame shim -> DataFrame with
        outputCol appended; array-like -> (n, k) ndarray.
        """
        if self._pc_raw is None:
            raise RuntimeError("model has no principal components")
        rows = extract_column(dataset, self.getInputCol())
        from spark_rapids_ml_tpu.core.data import (
            is_device_array,
            is_streaming_source,
            iter_stream_blocks,
        )

        if is_device_array(rows):
            # Device-resident projection through the serving program cache:
            # one AOT MXU matmul per (bucket, dtype), result stays on device
            # (the symmetric counterpart of the device-resident fit; the
            # batched path the reference disabled, RapidsPCA.scala:172-185).
            with TraceRange("device transform", TraceColor.GREEN):
                return serve_rows(
                    _project_kernel,
                    rows,
                    (self._pc_device(rows.dtype),),
                    static={"precision": self._serving_precision()},
                    name="pca.transform",
                )

        pc_dev = self._pc_device(self._serving_dtype())
        if is_streaming_source(rows):
            # Streaming in, streaming out: project block by block at
            # constant memory (the symmetric counterpart of streaming fit),
            # double-buffered — block k+1's H2D overlaps block k's GEMM.
            from spark_rapids_ml_tpu.core.data import _block_to_dense

            def dense_blocks():
                for blk in iter_stream_blocks(rows):
                    part = _block_to_dense(blk)
                    if part.shape[0] == 0:
                        # Empty partitions densify to (0, 0) — skip
                        # rather than matmul a widthless block.
                        continue
                    yield part

            with TraceRange("stream transform", TraceColor.GREEN):
                return serve_stream(
                    _project_kernel,
                    dense_blocks(),
                    (pc_dev,),
                    static={"precision": self._serving_precision()},
                    name="pca.transform",
                    dtype=pc_dev.dtype,
                )
        parts = as_partitions(rows)
        with TraceRange("batch transform", TraceColor.GREEN):
            outs = list(
                serve_stream(
                    _project_kernel,
                    parts,
                    (pc_dev,),
                    static={"precision": self._serving_precision()},
                    name="pca.transform",
                    dtype=pc_dev.dtype,
                )
            )
        if not outs:
            # All partitions empty: keep the (0, k) ndarray contract.
            projected = np.zeros((0, self.pc.shape[1]), dtype=self.pc.dtype)
        else:
            projected = np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
        if isinstance(dataset, DataFrame):
            return dataset.withColumn(self.getOutputCol(), list(projected))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out_df = dataset.copy()
                out_df[self.getOutputCol()] = list(projected)
                return out_df
        except ImportError:  # pragma: no cover
            pass
        return projected

    def _pc_device(self, dtype):
        """Components as a device array at ``dtype``, cached — repeated
        device transforms must not pay a host->device copy per call."""
        import jax.numpy as jnp

        key = str(dtype)
        if key not in self._pc_dev_cache:
            self._pc_dev_cache[key] = jnp.asarray(self._pc_raw).astype(dtype)
            note_device_cache(self)
        return self._pc_dev_cache[key]

    def _serving_dtype(self):
        """Compute dtype for host-batch serving: the components' own dtype,
        canonicalized (f64 under x64, f32 otherwise) — one program set per
        model, however the batch dtypes wander."""
        import jax

        return jax.dtypes.canonicalize_dtype(self.pc.dtype)

    def _serving_precision(self) -> str:
        """The serving-family policy mode (ops/precision.py): an explicit
        estimator ``setPrecision`` survives into the model and wins
        (non-GEMM modes like 'auto'/'dd' serve at 'highest'); otherwise
        the TPUML_PRECISION[_SERVING] knobs and committed autotune
        decisions apply. Part of the static dict, hence of the
        AOT/program cache key."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        if requested in ("auto", "dd"):
            requested = "highest"
        return resolve_policy("serving", requested)

    def serving_signature(self):
        """The online-serving contract: the projection kernel, the
        device-resident components at the serving dtype, and the (n, k)
        projected output spec."""
        import jax

        from spark_rapids_ml_tpu.serving.signature import ServingSignature

        if self._pc_raw is None:
            raise RuntimeError("model has no principal components")
        pc = self._pc_device(self._serving_dtype())
        d, k = int(pc.shape[0]), int(pc.shape[1])
        return ServingSignature(
            kernel=_project_kernel,
            weights=(pc,),
            static={"precision": self._serving_precision()},
            name="pca.transform",
            n_features=d,
            output_spec=lambda n, dtype: (
                jax.ShapeDtypeStruct((n, k), dtype),
            ),
        )

    # --- persistence (RapidsPCA.scala:207-255) ---

    def _save_impl(self, path: str) -> None:
        save_metadata(self, path, class_name="com.nvidia.spark.ml.feature.PCAModel")
        save_data(
            path,
            {
                "pc": ("matrix", self.pc),
                "explainedVariance": ("vector", self.explainedVariance),
            },
        )

    @classmethod
    def _load_impl(cls, path: str) -> "PCAModel":
        metadata = load_metadata(path, expected_class="PCAModel")
        data = load_data(path)
        model = cls(metadata["uid"], data["pc"], data["explainedVariance"])
        get_and_set_params(model, metadata)
        return model
