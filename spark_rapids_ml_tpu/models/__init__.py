from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from spark_rapids_ml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_rapids_ml_tpu.models.nearest_neighbors import (
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.dbscan import DBSCAN, DBSCANModel
from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.random_forest import (
    RandomForestClassifier,
    RandomForestClassificationModel,
    RandomForestRegressor,
    RandomForestRegressionModel,
)
from spark_rapids_ml_tpu.models.umap import UMAP, UMAPModel

__all__ = [
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
    "DBSCAN",
    "DBSCANModel",
    "PCA",
    "PCAModel",
    "KMeans",
    "KMeansModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "UMAP",
    "UMAPModel",
]
