"""LinearRegression estimator/model — Spark ML surface, normal-equation solver.

Param surface mirrors ``org.apache.spark.ml.regression.LinearRegression``:
``featuresCol``, ``labelCol``, ``predictionCol``, ``fitIntercept``,
``regParam``, ``elasticNetParam`` (0 -> Ridge via the exact normal-equation
solve; > 0 -> Lasso/elastic net via FISTA on the same sufficient
statistics — solver="normal" rejects it, as in Spark), ``standardization``,
``solver`` ("normal" | "auto"). Beyond-the-reference capability
(BASELINE.md config 4).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    as_matrix,
    extract_weights,
    is_device_array,
)
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.ingest import matrix_like, prepare_labels, prepare_rows
from spark_rapids_ml_tpu.core.lazy_state import LazyHostState
from spark_rapids_ml_tpu.core.params import Param, Params, toBoolean, toFloat, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_data,
    load_metadata,
    save_data,
    save_metadata,
)
from spark_rapids_ml_tpu.ops.linear import (
    normal_eq_stats,
    normal_eq_stats_streaming,
    predict_linear,
    regression_metrics,
    solve_elastic_net,
    solve_elastic_net_resumable,
    solve_normal,
    solve_normal_host,
)
from spark_rapids_ml_tpu.core.serving import note_device_cache, serve_rows
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


def _predict_kernel(x, coef, intercept, *, precision: str = "highest"):
    """Serving kernel: X·coef + b. Coefficients follow the batch dtype
    (the model-side convention; the cast fuses into the GEMM).
    ``precision`` is the resolved serving-family policy mode
    (ops/precision.py) — static, so it keys the AOT program cache."""
    return predict_linear(
        x, coef.astype(x.dtype), intercept.astype(x.dtype),
        precision=precision,
    )


class _LinearRegressionParams(Params):
    featuresCol = Param("_", "featuresCol", "features column name", toString)
    labelCol = Param("_", "labelCol", "label column name", toString)
    predictionCol = Param("_", "predictionCol", "prediction column name", toString)
    fitIntercept = Param("_", "fitIntercept", "whether to fit an intercept", toBoolean)
    regParam = Param("_", "regParam", "L2 regularization strength", toFloat)
    elasticNetParam = Param("_", "elasticNetParam", "L1/L2 mixing (0 = pure L2)", toFloat)
    standardization = Param(
        "_", "standardization", "penalize standardized coefficients", toBoolean
    )
    solver = Param("_", "solver", "normal or auto", toString)
    weightCol = Param("_", "weightCol", "per-row weight column name", toString)
    precision = Param(
        "_",
        "precision",
        "auto | default | high | highest | dd (double-float fp64 emulation)",
        toString,
    )

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            fitIntercept=True,
            regParam=0.0,
            elasticNetParam=0.0,
            standardization=True,
            solver="auto",
            precision="auto",
        )

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)

    def getStandardization(self) -> bool:
        return self.getOrDefault(self.standardization)

    def getSolver(self) -> str:
        return self.getOrDefault(self.solver)

    def getWeightCol(self) -> Optional[str]:
        return (
            self.getOrDefault(self.weightCol)
            if self.isDefined(self.weightCol)
            else None
        )

    def getPrecision(self) -> str:
        return self.getOrDefault(self.precision)


class LinearRegression(_LinearRegressionParams, Estimator, MLReadable):
    """OLS / Ridge via the normal-equation GEMM path.

    ``LinearRegression().setRegParam(0.1).fit((X, y))`` — input is
    ``(X, y)``, a DataFrame shim / pandas frame with features+label columns.
    """

    # Consumes device (X, y) pairs in place, so tuning loops may feed
    # device-resident fold slices (tuning._device_fold_prep).
    _device_foldable = True

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh

    def setFeaturesCol(self, value: str) -> "LinearRegression":
        self.set(self.featuresCol, value)
        return self

    def setLabelCol(self, value: str) -> "LinearRegression":
        self.set(self.labelCol, value)
        return self

    def setPredictionCol(self, value: str) -> "LinearRegression":
        self.set(self.predictionCol, value)
        return self

    def setFitIntercept(self, value: bool) -> "LinearRegression":
        self.set(self.fitIntercept, value)
        return self

    def setRegParam(self, value: float) -> "LinearRegression":
        if value < 0:
            raise ValueError(f"regParam must be >= 0, got {value}")
        self.set(self.regParam, value)
        return self

    def setElasticNetParam(self, value: float) -> "LinearRegression":
        if not 0.0 <= value <= 1.0:
            raise ValueError(f"elasticNetParam must be in [0, 1], got {value}")
        self.set(self.elasticNetParam, value)
        return self

    def setStandardization(self, value: bool) -> "LinearRegression":
        self.set(self.standardization, value)
        return self

    def setSolver(self, value: str) -> "LinearRegression":
        if value not in ("normal", "auto"):
            raise ValueError(f"solver must be 'normal' or 'auto', got {value!r}")
        self.set(self.solver, value)
        return self

    def setWeightCol(self, value: str) -> "LinearRegression":
        self.set(self.weightCol, value)
        return self

    def setPrecision(self, value: str) -> "LinearRegression":
        """Matmul precision for the sufficient-statistics GEMMs. ``"dd"``
        emulates fp64 via double-float MXU GEMMs (ops.doubledouble) and
        solves the normal equations in host fp64 — the reference's
        ``double[]`` numerics (JniRAPIDSML.java:64-69) on fp32-only
        hardware; ``"auto"`` selects it for float64 input without x64."""
        from spark_rapids_ml_tpu.ops.linalg import validate_precision

        self.set(self.precision, validate_precision(value))
        return self

    def setMesh(self, mesh) -> "LinearRegression":
        self.mesh = mesh
        return self

    _initial_coef = None  # (d,) FISTA warm start, original space
    _copy_attrs = ("_initial_coef",)

    def setInitialModel(self, value) -> "LinearRegression":
        """Warm start the FISTA solve from an existing model's
        coefficients (or a raw ``(d,)`` array) — the incremental-refit
        seed (lifecycle/partial_fit.py). Applies to the elastic-net
        path; the exact normal-equation solve has no iteration to seed
        and rejects it at fit time."""
        coef = value.coefficients if hasattr(value, "coefficients") else value
        coef = np.asarray(coef, dtype=np.float64)
        if coef.ndim != 1:
            raise ValueError("initial model/coefficients must be a (d,) vector")
        self._initial_coef = coef
        return self

    def _uses_fista(self) -> bool:
        """True when the fit routes to the proximal (FISTA) solver rather
        than the exact normal-equation solve (see _solve_from_stats)."""
        return self.getElasticNetParam() > 0.0 and self.getRegParam() > 0.0

    def _raw_features_dtype(self, dataset):
        """Dtype of the raw user feature container, probed before any
        float64 coercion (core.data.infer_input_dtype) — the gate for
        precision='auto' dd routing."""
        from spark_rapids_ml_tpu.core.data import infer_input_dtype

        if isinstance(dataset, tuple) and len(dataset) == 2:
            return infer_input_dtype(dataset[0])
        if isinstance(dataset, DataFrame):
            return infer_input_dtype(dataset.select(self.getFeaturesCol()))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                fc = self.getFeaturesCol()
                if fc in dataset.columns:
                    return infer_input_dtype(dataset[fc])
                return infer_input_dtype(
                    dataset.drop(columns=[self.getLabelCol()], errors="ignore")
                )
        except ImportError:  # pragma: no cover
            pass
        return infer_input_dtype(dataset)

    def _resolved_precision(self, dataset) -> str:
        """Resolve the precision request to a concrete mode for this fit.
        Resolution policy lives in :meth:`RowMatrix.resolve` (the single
        home); this adds only the estimator-specific dd blockers: explicit
        ``precision='dd'`` raises on combinations that have no dd route
        (mesh, weightCol, FISTA); ``'auto'`` quietly falls back to
        ``'highest'`` for those."""
        from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision()
        # Only "auto" needs the dtype probe; explicit values pass through.
        input_dtype = (
            self._raw_features_dtype(dataset) if requested == "auto" else None
        )
        # Mixed-precision policy layering (ops/precision.py): explicit
        # setPrecision > TPUML_PRECISION[_LINEAR] knobs > committed
        # autotune decision > the param default. fp64 input keeps its
        # pre-policy "auto" dd routing — the tuner never displaces fp64
        # emulation.
        explicit = self.getPrecision() if self.isSet(self.precision) else None
        wants_f64 = input_dtype is not None and np.dtype(input_dtype) == np.float64
        if explicit is None and wants_f64:
            explicit = "auto"
        requested = resolve_policy("linear", explicit, default=requested)
        resolved = RowMatrix.resolve(
            requested, mesh=self.mesh, input_dtype=input_dtype
        )
        if resolved != "dd":
            return resolved
        blockers = []
        if self.mesh is not None:
            blockers.append("a mesh (dd is single-device)")
        if self.getWeightCol() is not None:
            blockers.append("weightCol")
        if self._uses_fista():
            blockers.append("elastic net (FISTA)")
        if blockers:
            if requested == "dd":
                raise ValueError(
                    "precision='dd' does not support " + ", ".join(blockers)
                )
            return "highest"
        return "dd"

    def _fit_dd(self, block_pairs) -> "LinearRegressionModel":
        """Extended-precision fit: dd GEMM moments + host fp64 solve."""
        from spark_rapids_ml_tpu.ops.doubledouble import normal_eq_stats_dd

        with TraceRange("linreg dd fit", TraceColor.DARK_GREEN):
            xtx, xty, x_sum, y_sum, _, count = normal_eq_stats_dd(block_pairs)
            coef, intercept = solve_normal_host(
                xtx,
                xty,
                x_sum,
                y_sum,
                count,
                reg_param=self.getRegParam(),
                fit_intercept=self.getFitIntercept(),
                standardization=self.getStandardization(),
            )
        model = LinearRegressionModel(
            self.uid, np.asarray(coef, dtype=np.float64), float(intercept)
        )
        return self._copyValues(model)

    def _fit(self, dataset: Any) -> "LinearRegressionModel":
        if self.getElasticNetParam() > 0.0 and self.getSolver() == "normal":
            # Spark's normal solver rejects L1 the same way; validate before
            # any data movement or GEMM work.
            raise ValueError(
                "solver='normal' supports only L2 (elasticNetParam must "
                "be 0); use solver='auto' for elastic net"
            )
        streaming = None
        if self.mesh is None and self.getWeightCol() is None:
            streaming = _streaming_blocks(dataset)
        if streaming is not None:
            # Blocks (list or generator of (rows_i, d) arrays) accumulate
            # their sufficient statistics one block at a time — every solver
            # below consumes only the O(d^2) moments, so device memory is
            # bounded by one block (pairs with native.NpyBlockReader).
            # Precision resolution probes the dataset container, never the
            # stream, so the generator passes through unconsumed.
            prec = self._resolved_precision(dataset)
            if prec == "dd":
                return self._fit_dd(streaming)
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            with TraceRange("linreg fit", TraceColor.DARK_GREEN):
                stats = normal_eq_stats_streaming(
                    streaming, dtype=dtype, precision=prec
                )
                coef, intercept = self._solve_from_stats(stats, stats[0].shape[0])
            model = LinearRegressionModel(
                self.uid, np.asarray(coef, dtype=np.float64), float(intercept)
            )
            return self._copyValues(model)

        x_in, y_in = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        w_host = extract_weights(dataset, self.getWeightCol())
        prec = self._resolved_precision(dataset)
        from spark_rapids_ml_tpu.core import membudget

        # Budgeted admission (core/membudget.py): an over-budget host
        # input reroutes through a block reader into the SAME streaming
        # sufficient-statistics branch above — bit-identical by
        # construction — and a device OOM mid-fit reclaims caches and
        # takes the same exit.
        can_stream = w_host is None
        guard = membudget.fit_memory_guard(
            "linear", x_in, can_stream=can_stream,
            why_cannot_stream="the streaming path does not support weightCol",
            mesh=self.mesh, ledger_families=("linear", "linreg"),
        )
        if guard.degrade:
            return membudget.run_streaming_with_recovery(
                "linear", lambda r: self._fit((r, y_in)), guard.matrix
            )
        fallback = (
            (lambda: membudget.run_streaming_with_recovery(
                "linear", lambda r: self._fit((r, y_in)),
                membudget.host_matrix(x_in)))
            if can_stream and self.mesh is None else None
        )
        return membudget.run_fit_with_oom_recovery(
            "linear", lambda: self._fit_in_memory(x_in, y_in, w_host, prec),
            fallback,
        )

    def _fit_in_memory(self, x_in, y_in, w_host, prec) -> "LinearRegressionModel":
        if prec == "dd":
            if is_device_array(x_in):
                # Same stance as PCA: dd operands split on HOST fp64 — a
                # device array has no fp64 bits left to split.
                raise ValueError(
                    "precision='dd' does not support device-array input "
                    "(the hi/lo split consumes the host fp64 source)"
                )
            return self._fit_dd([(x_in, y_in)])

        with TraceRange("linreg fit", TraceColor.DARK_GREEN):
            # One funnel for every residence: device arrays fit in place
            # (VERDICT r3 #1), host data places once, dtype-preserving.
            xs, mask, n, d = prepare_rows(x_in, mesh=self.mesh, weights=w_host)
            ys = prepare_labels(
                y_in, int(xs.shape[0]), n_true=n, mesh=self.mesh, dtype=xs.dtype
            )
            # Uniform unmasked case: skip the x*mask pass (bytes-bound at
            # small d — the multiply would double the HBM traffic).
            if w_host is None and self.mesh is None:
                mask = None
            stats = normal_eq_stats(xs, ys, mask, precision=prec)
            # Gang deploy mode: the solve below reads the O(d²) statistics
            # on the host — replicate them so every member solves the
            # identical whole-dataset normal equations (no-op otherwise).
            from spark_rapids_ml_tpu.parallel.distributed import (
                replicate_for_host,
            )

            stats = replicate_for_host(self.mesh, *stats)
            coef, intercept = self._solve_from_stats(stats, d)

        # Solve outputs stay device-resident; the model's host float64
        # views convert lazily (the PCAModel contract).
        model = LinearRegressionModel(self.uid, coef, intercept)
        return self._copyValues(model)

    def _solve_from_stats(self, stats, d: int):
        """Dispatch the solver on the accumulated sufficient statistics —
        the one home of the exact-vs-proximal routing (shared by the
        in-memory, mesh, and streaming fit paths)."""
        xtx, xty, x_sum, y_sum, yty, count = stats
        init_coef = self._initial_coef
        if init_coef is not None and init_coef.shape[0] != d:
            raise ValueError(
                f"initial model has {init_coef.shape[0]} coefficients, "
                f"data has {d} features"
            )
        if not self._uses_fista():
            if init_coef is not None:
                raise ValueError(
                    "setInitialModel warm start applies to the elastic-net "
                    "(FISTA) path (elasticNetParam > 0 and regParam > 0); "
                    "the exact normal-equation solve has no iteration to seed"
                )
            # Zero effective penalty: the exact (Cholesky) solve, not a
            # fixed-step proximal approximation of the same objective.
            return solve_normal(
                xtx[:d, :d],
                xty[:d],
                x_sum[:d],
                y_sum,
                count,
                reg_param=self.getRegParam(),
                fit_intercept=self.getFitIntercept(),
                standardization=self.getStandardization(),
            )
        # L1/elastic net: FISTA on the same sufficient statistics — one
        # data GEMM pass, then O(d^2) proximal iterations (Spark reaches
        # this case via OWL-QN over the data). With the TPUML_CHECKPOINT_*
        # knobs set the proximal loop runs segmented with async snapshots
        # and resumes mid-solve (robustness/checkpoint.py); the iterative
        # loop — not the one-GEMM stats pass — is what preemption loses.
        ckpt = self._fit_checkpointer(
            "linreg.fista", data=(xtx[:d, :d], xty[:d], x_sum[:d], y_sum, count)
        )
        if ckpt is not None:
            coef, intercept, _ = solve_elastic_net_resumable(
                xtx[:d, :d],
                xty[:d],
                x_sum[:d],
                y_sum,
                count,
                reg_param=self.getRegParam(),
                elastic_net_param=self.getElasticNetParam(),
                checkpointer=ckpt,
                fit_intercept=self.getFitIntercept(),
                standardization=self.getStandardization(),
                init_coef=init_coef,
                mesh=self.mesh,
            )
            return coef, intercept
        coef, intercept, _ = solve_elastic_net(
            xtx[:d, :d],
            xty[:d],
            x_sum[:d],
            y_sum,
            count,
            reg_param=self.getRegParam(),
            elastic_net_param=self.getElasticNetParam(),
            fit_intercept=self.getFitIntercept(),
            standardization=self.getStandardization(),
            init_coef=init_coef,
        )
        return coef, intercept


def _streaming_blocks(dataset):
    """Detect the streaming input form: ``(X, y)`` where X is a list of 2-D
    blocks (dense or scipy-sparse) or any iterator of them (e.g.
    ``NpyBlockReader.iter_blocks()``). Returns an iterator of
    (X_block, y_block) pairs, or None when the input is not block-shaped.

    A single y array is sliced along the block boundaries and must match
    the total row count exactly; a list of per-block label arrays must have
    one entry per block — both mismatches raise instead of silently
    truncating.
    """
    from spark_rapids_ml_tpu.core.data import (
        _block_to_dense,
        _is_block,
        is_streaming_source,
        iter_stream_blocks,
    )

    if not (isinstance(dataset, tuple) and len(dataset) == 2):
        return None
    x, y = dataset
    if isinstance(x, (list, tuple)) and x and _is_block(x[0]):
        blocks = iter(x)
    elif is_streaming_source(x):
        blocks = iter_stream_blocks(x)
    else:
        return None

    def pairs():
        if isinstance(y, (list, tuple)):
            sentinel = object()
            from itertools import zip_longest

            for xb, yb in zip_longest(blocks, y, fillvalue=sentinel):
                if xb is sentinel or yb is sentinel:
                    raise ValueError(
                        "streaming fit: X blocks and per-block label lists "
                        "have different lengths"
                    )
                yield _block_to_dense(xb), yb
            return
        y_arr = np.asarray(y).ravel()
        start = 0
        for xb in blocks:
            xb = _block_to_dense(xb)
            yb = y_arr[start : start + xb.shape[0]]
            # Check the slice HERE, not downstream: the double-buffered
            # accumulator prepares pair k+1 before consuming pair k, so a
            # short tail must fail when it is produced to fail at all.
            if yb.shape[0] != xb.shape[0]:
                raise ValueError(
                    f"block rows mismatch: X block has {xb.shape[0]} rows "
                    f"but only {yb.shape[0]} labels remain"
                )
            yield xb, yb
            start += xb.shape[0]
        if start != y_arr.shape[0]:
            raise ValueError(
                f"streaming fit: blocks supplied {start} rows but y has "
                f"{y_arr.shape[0]}"
            )

    return pairs()


def _extract_xy(dataset: Any, features_col: str, label_col: str):
    """Accepts (X, y) tuples, DataFrame shim, or pandas with named columns."""
    if isinstance(dataset, tuple) and len(dataset) == 2:
        x, y = dataset
        if is_device_array(x):
            # Device-resident X: consumed in place by the prepare_rows
            # funnel. y keeps its device residence when it has one;
            # host-side y (list/ndarray) still normalizes to float64 —
            # downstream code relies on ndarray semantics (.size, math).
            if is_device_array(y):
                return x, y
            return x, np.asarray(y, dtype=np.float64).ravel()
        return as_matrix(x), np.asarray(y, dtype=np.float64).ravel()
    if isinstance(dataset, DataFrame):
        x = as_matrix(dataset.select(features_col))
        y = np.asarray(dataset.select(label_col), dtype=np.float64).ravel()
        return x, y
    try:
        import pandas as pd

        if isinstance(dataset, pd.DataFrame):
            if features_col in dataset.columns:
                x = as_matrix(dataset[features_col].tolist())
            else:
                x = dataset.drop(columns=[label_col]).to_numpy(dtype=np.float64)
            y = dataset[label_col].to_numpy(dtype=np.float64)
            return x, y
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(
        "dataset must be (X, y), a DataFrame with features/label columns, or a pandas DataFrame"
    )


class LinearRegressionModel(_LinearRegressionParams, Model, LazyHostState):
    """Fitted model: ``coefficients`` (d,), ``intercept``.

    Fitted state may be host numpy OR live jax.Arrays from a device-
    resident fit; host float64 views convert lazily and pickling
    materializes host state (core/lazy_state.LazyHostState)."""

    _lazy_host_fields = {"_coef_raw": ("_coef_np", np.float64)}
    _pickle_clear = ("_coef_dev",)

    def __init__(
        self,
        uid: Optional[str] = None,
        coefficients: Optional[np.ndarray] = None,
        intercept: float = 0.0,
    ):
        super().__init__(uid)
        self._coef_raw = coefficients
        self._coef_np: Optional[np.ndarray] = None
        self._coef_dev = None
        self._intercept_raw = intercept

    def __getstate__(self):
        state = super().__getstate__()
        state["_intercept_raw"] = self.intercept
        return state

    @property
    def coefficients(self) -> Optional[np.ndarray]:
        return self._lazy_host_view("_coef_raw")

    @property
    def intercept(self) -> float:
        if not isinstance(self._intercept_raw, float):
            self._intercept_raw = float(self._intercept_raw)
        return self._intercept_raw

    def copy(self, extra=None) -> "LinearRegressionModel":
        """Model.copy preserves fitted state (Spark's Model.copy contract)."""
        that = LinearRegressionModel(self.uid, self._coef_raw, self._intercept_raw)
        return self._copyValues(that, extra)

    def predict(self, x) -> np.ndarray:
        if self._coef_raw is None:
            raise RuntimeError("model has no coefficients")
        # Device queries get device predictions; host queries keep numpy.
        # Both run through the shape-bucketed serving program cache.
        return serve_rows(
            _predict_kernel,
            matrix_like(x),
            self._coef_serving(),
            static={"precision": self._serving_precision()},
            name="linreg.predict",
        )

    def _serving_precision(self) -> str:
        """The serving-family policy mode (ops/precision.py): an explicit
        estimator ``setPrecision`` survives into the model and wins
        (non-GEMM modes like 'auto'/'dd' serve at 'highest'); otherwise
        the TPUML_PRECISION[_SERVING] knobs and committed autotune
        decisions apply. Part of the static dict, hence of the
        AOT/program cache key."""
        from spark_rapids_ml_tpu.ops.precision import resolve_policy

        requested = self.getPrecision() if self.isSet(self.precision) else None
        if requested in ("auto", "dd"):
            requested = "highest"
        return resolve_policy("serving", requested)

    def _coef_serving(self):
        """(coefficients, intercept) as ONE device-resident pair reused by
        every predict call."""
        if self._coef_dev is None:
            coef = (
                self._coef_raw
                if is_device_array(self._coef_raw)
                else jnp.asarray(self.coefficients)
            )
            self._coef_dev = (coef, jnp.asarray(self._intercept_raw))
            note_device_cache(self)
        return self._coef_dev

    def serving_signature(self):
        """The online-serving contract: the X·coef + b kernel, the
        device-resident (coefficients, intercept) pair, and the (n,)
        prediction output spec."""
        import jax

        from spark_rapids_ml_tpu.serving.signature import ServingSignature

        if self._coef_raw is None:
            raise RuntimeError("model has no coefficients")
        coef, intercept = self._coef_serving()
        return ServingSignature(
            kernel=_predict_kernel,
            weights=(coef, intercept),
            static={"precision": self._serving_precision()},
            name="linreg.predict",
            n_features=int(coef.shape[0]),
            output_spec=lambda n, dtype: (
                jax.ShapeDtypeStruct((n,), dtype),
            ),
        )

    def transform(self, dataset: Any) -> Any:
        if isinstance(dataset, tuple):
            x = dataset[0]
        else:
            x = dataset
        if isinstance(dataset, DataFrame):
            pred = self.predict(dataset.select(self.getFeaturesCol()))
            return dataset.withColumn(self.getPredictionCol(), list(pred))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                if self.getFeaturesCol() in dataset.columns:
                    pred = self.predict(dataset[self.getFeaturesCol()].tolist())
                else:
                    cols = [c for c in dataset.columns if c != self.getLabelCol()]
                    pred = self.predict(dataset[cols].to_numpy(dtype=np.float64))
                out = dataset.copy()
                out[self.getPredictionCol()] = pred
                return out
        except ImportError:  # pragma: no cover
            pass
        return self.predict(x)

    def evaluate(self, dataset: Any) -> dict:
        """RegressionSummary analogue: mse/rmse/mae/r2 on a labeled dataset."""
        x, y = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        pred = self.predict(x)
        mask = jnp.ones(len(y), dtype=pred.dtype)
        mse, rmse, mae, r2 = regression_metrics(jnp.asarray(y, dtype=pred.dtype), jnp.asarray(pred), mask)
        return {
            "meanSquaredError": float(mse),
            "rootMeanSquaredError": float(rmse),
            "meanAbsoluteError": float(mae),
            "r2": float(r2),
        }

    def _save_impl(self, path: str) -> None:
        save_metadata(
            self, path, class_name="org.apache.spark.ml.regression.LinearRegressionModel"
        )
        save_data(
            path,
            {
                "coefficients": ("vector", self.coefficients),
                "intercept": ("scalar", float(self.intercept)),
            },
        )

    @classmethod
    def _load_impl(cls, path: str) -> "LinearRegressionModel":
        metadata = load_metadata(path, expected_class="LinearRegressionModel")
        data = load_data(path)
        model = cls(metadata["uid"], data["coefficients"], float(data["intercept"]))
        get_and_set_params(model, metadata)
        return model
