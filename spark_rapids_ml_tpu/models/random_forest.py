"""RandomForestClassifier / RandomForestRegressor — Spark ML surface, XLA compute.

Param surface mirrors ``org.apache.spark.ml.classification.RandomForestClassifier``
and ``...regression.RandomForestRegressor``: ``numTrees``, ``maxDepth``,
``maxBins``, ``minInstancesPerNode``, ``minInfoGain``, ``subsamplingRate``,
``featureSubsetStrategy``, ``impurity``, ``bootstrap``, ``seed``, plus the
usual column params. Beyond-the-reference capability (the reference repo
ships only PCA — SURVEY.md §2; the modern RAPIDS Spark-ML line accelerates
random forests via cuML), so the test oracle is scikit-learn / handcrafted
separable data rather than a reference file.

All trees grow simultaneously, level by level, with histogram GEMMs on the
MXU — see :mod:`spark_rapids_ml_tpu.ops.trees` for the kernel design.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    DataFrame,
    extract_features,
    extract_weights,
    is_device_array,
)
from spark_rapids_ml_tpu.core.estimator import Estimator, Model
from spark_rapids_ml_tpu.core.ingest import matrix_like, validate_int_labels
from spark_rapids_ml_tpu.core.params import Param, Params, toBoolean, toFloat, toInt, toString
from spark_rapids_ml_tpu.core.persistence import (
    MLReadable,
    get_and_set_params,
    load_metadata,
    load_rows,
    save_metadata,
)
from spark_rapids_ml_tpu.models.linear_regression import _extract_xy
from spark_rapids_ml_tpu.ops.trees import (
    Forest,
    bin_features,
    feature_importances,
    fit_forest_fused,
    forest_predict_proba,
    forest_predict_reg,
    grow_forest_sharded,
    quantize_features,
    sample_weights,
)
from spark_rapids_ml_tpu.core.serving import note_device_cache, serve_rows
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


def _proba_kernel(x, forest, *, depth: int):
    """Serving kernel: (n, C) mean leaf class distributions. Trees route
    in float32 (the forests' training dtype)."""
    return forest_predict_proba(x.astype(jnp.float32), forest, depth)


def _reg_kernel(x, forest, *, depth: int):
    """Serving kernel: (n,) mean leaf values."""
    return forest_predict_reg(x.astype(jnp.float32), forest, depth)


def _forest_device(model):
    """The model's forest as ONE device-resident pytree reused by every
    predict call (host pickles drop it; it rebuilds lazily)."""
    if model._forest_dev is None:
        model._forest_dev = jax.tree_util.tree_map(jnp.asarray, model._forest)
        note_device_cache(model)
    return model._forest_dev


def _select_argmax(outs):
    """Transform-contract selection for the fuser: the classifier's
    ``transform`` on a plain array yields argmax labels, not the class
    distribution — selecting in-program lets XLA drop the probability
    writes when a fused pipeline ends in a forest classifier."""
    probs = outs[0] if isinstance(outs, tuple) else outs
    return jnp.argmax(probs, axis=1)


def _forest_signature(model, kernel, name, output_spec, select=None):
    """Shared ``serving_signature()`` body for the two forest models."""
    from spark_rapids_ml_tpu.serving.signature import ServingSignature

    if model._forest is None:
        raise RuntimeError("model has no fitted forest")
    return ServingSignature(
        kernel=kernel,
        weights=(_forest_device(model),),
        static={"depth": _forest_depth(model._forest)},
        name=name,
        n_features=int(model.numFeatures),
        output_spec=output_spec,
        select=select,
    )


def resolve_feature_subset(strategy: str, d: int, n_trees: int, classification: bool) -> int:
    """Spark's featureSubsetStrategy -> number of features per split."""
    s = strategy.lower()
    if s == "auto":
        if n_trees == 1:
            return d
        return (
            max(1, int(math.ceil(math.sqrt(d))))
            if classification
            else max(1, int(math.ceil(d / 3.0)))
        )
    if s == "all":
        return d
    if s == "sqrt":
        return max(1, int(math.ceil(math.sqrt(d))))
    if s == "log2":
        return max(1, int(math.ceil(math.log2(max(d, 2)))))
    if s == "onethird":
        return max(1, int(math.ceil(d / 3.0)))
    # Spark's grammar: an all-digits string is an absolute count in [1, d];
    # anything with a decimal point is a fraction in (0, 1] of the features
    # (so "1.0" means ALL features, not one).
    try:
        count = int(strategy)
    except ValueError:
        count = None
    if count is not None:
        if count < 1:
            raise ValueError(
                f"featureSubsetStrategy integer must be >= 1, got {strategy!r}"
            )
        return min(d, count)
    try:
        v = float(strategy)
    except ValueError:
        raise ValueError(f"unknown featureSubsetStrategy {strategy!r}")
    if 0 < v <= 1:
        return max(1, int(math.ceil(v * d)))
    raise ValueError(f"unknown featureSubsetStrategy {strategy!r}")


class _RandomForestParams(Params):
    numTrees = Param("_", "numTrees", "number of trees", toInt)
    maxDepth = Param("_", "maxDepth", "maximum tree depth", toInt)
    maxBins = Param("_", "maxBins", "max histogram bins per feature", toInt)
    minInstancesPerNode = Param(
        "_", "minInstancesPerNode", "min instances each child must have", toInt
    )
    minInfoGain = Param("_", "minInfoGain", "min info gain for a split", toFloat)
    subsamplingRate = Param("_", "subsamplingRate", "row sampling rate per tree", toFloat)
    featureSubsetStrategy = Param(
        "_", "featureSubsetStrategy", "features considered per split", toString
    )
    impurity = Param("_", "impurity", "split criterion", toString)
    bootstrap = Param("_", "bootstrap", "sample with replacement", toBoolean)
    seed = Param("_", "seed", "random seed", toInt)
    featuresCol = Param("_", "featuresCol", "features column name", toString)
    labelCol = Param("_", "labelCol", "label column name", toString)
    predictionCol = Param("_", "predictionCol", "prediction column name", toString)
    weightCol = Param("_", "weightCol", "per-row weight column name", toString)

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid)
        self._setDefault(
            numTrees=20,
            maxDepth=5,
            maxBins=32,
            minInstancesPerNode=1,
            minInfoGain=0.0,
            subsamplingRate=1.0,
            featureSubsetStrategy="auto",
            bootstrap=True,
            seed=0,
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
        )

    def getNumTrees(self) -> int:
        return self.getOrDefault(self.numTrees)

    def getMaxDepth(self) -> int:
        return self.getOrDefault(self.maxDepth)

    def getMaxBins(self) -> int:
        return self.getOrDefault(self.maxBins)

    def getMinInstancesPerNode(self) -> int:
        return self.getOrDefault(self.minInstancesPerNode)

    def getMinInfoGain(self) -> float:
        return self.getOrDefault(self.minInfoGain)

    def getSubsamplingRate(self) -> float:
        return self.getOrDefault(self.subsamplingRate)

    def getFeatureSubsetStrategy(self) -> str:
        return self.getOrDefault(self.featureSubsetStrategy)

    def getImpurity(self) -> str:
        return self.getOrDefault(self.impurity)

    def getBootstrap(self) -> bool:
        return self.getOrDefault(self.bootstrap)

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)

    def getWeightCol(self) -> Optional[str]:
        return (
            self.getOrDefault(self.weightCol)
            if self.isDefined(self.weightCol)
            else None
        )

    # Chainable setters shared by estimators and models.
    def _chain(self, param, value):
        self.set(param, value)
        return self

    def setNumTrees(self, v: int):
        if v < 1:
            raise ValueError(f"numTrees must be >= 1, got {v}")
        return self._chain(self.numTrees, v)

    def setMaxDepth(self, v: int):
        if not 0 <= v <= 14:
            raise ValueError(f"maxDepth must be in [0, 14], got {v}")
        return self._chain(self.maxDepth, v)

    def setMaxBins(self, v: int):
        if v < 2:
            raise ValueError(f"maxBins must be >= 2, got {v}")
        return self._chain(self.maxBins, v)

    def setMinInstancesPerNode(self, v: int):
        if v < 1:
            raise ValueError(f"minInstancesPerNode must be >= 1, got {v}")
        return self._chain(self.minInstancesPerNode, v)

    def setMinInfoGain(self, v: float):
        return self._chain(self.minInfoGain, v)

    def setSubsamplingRate(self, v: float):
        if not 0 < v <= 1:
            raise ValueError(f"subsamplingRate must be in (0, 1], got {v}")
        return self._chain(self.subsamplingRate, v)

    def setFeatureSubsetStrategy(self, v: str):
        return self._chain(self.featureSubsetStrategy, v)

    def setBootstrap(self, v: bool):
        return self._chain(self.bootstrap, v)

    def setSeed(self, v: int):
        return self._chain(self.seed, v)

    def setFeaturesCol(self, v: str):
        return self._chain(self.featuresCol, v)

    def setLabelCol(self, v: str):
        return self._chain(self.labelCol, v)

    def setPredictionCol(self, v: str):
        return self._chain(self.predictionCol, v)

    def setWeightCol(self, v: str):
        return self._chain(self.weightCol, v)


@jax.jit
def _exactness_device(rs, w):
    """Fused device-side bf16-exactness predicate: ONE scalar readback
    (integrality of stats AND weights, and the max product bound) — a
    device-resident fit must not pull the (n, S) one-hot to host, and
    even the host path should pay one sync, not two (each readback is a
    full round trip through a relay tunnel)."""
    rs = rs.astype(jnp.float32)
    return (
        jnp.all(rs == jnp.rint(rs))
        & jnp.all(w == jnp.rint(w))
        & (jnp.max(jnp.abs(rs)) * jnp.max(w) <= 256.0)
    )


@jax.jit
def _weight_exact_and_max(w):
    """[weights_all_integer, max_weight] as one device array — one pull."""
    return jnp.stack(
        [jnp.all(w == jnp.rint(w)).astype(jnp.float32), jnp.max(w)]
    )


def _hist_exact_in_bf16(row_stats, sample_w) -> bool:
    """True when every histogram operand survives bf16 rounding. The
    one-pass DEFAULT-precision histogram feeds ``sample_weight * stat``
    to the MXU as bf16 (fp32 accumulation), so exactness needs the
    *product* — integer and <= 256 — not just the raw stats: an integer
    weightCol of 129 drawn 3 times by the bootstrap contributes 387,
    which bf16 rounds. Bootstrap draws are integral today
    (Poisson/Bernoulli), but the guard verifies that rather than assume
    it."""
    if is_device_array(row_stats):
        if row_stats.size == 0:
            return False
        return bool(_exactness_device(row_stats, jnp.asarray(sample_w)))
    rs = np.asarray(row_stats, dtype=np.float32)
    if rs.size == 0 or not np.array_equal(rs, np.rint(rs)):
        return False
    w_stats = np.asarray(_weight_exact_and_max(jnp.asarray(sample_w)))
    if not w_stats[0]:
        return False
    return float(np.abs(rs).max()) * float(w_stats[1]) <= 256.0


def _fit_forest(params: _RandomForestParams, x: np.ndarray, row_stats: np.ndarray,
                impurity: str, classification: bool, mesh=None,
                stats_integral: bool = False) -> Forest:
    """Shared fit: quantize, sample, grow. Returns the Forest arrays.

    Single-device fits run the WHOLE pipeline (quantile edges + binning +
    growth) as one XLA program (:func:`fit_forest_fused`, VERDICT r4 #2 —
    the prep used to cost more than the growth); only the sample-weight
    draw stays outside it, because the bf16-exactness predicate must read
    it back to pick the (static) histogram precision before compiling.

    With a mesh, rows are data-sharded and the per-level histograms merge
    over ICI (:func:`grow_forest_sharded`); quantization and weight sampling
    stay replicated (edges/weights are tiny and seed-deterministic)."""
    from spark_rapids_ml_tpu.core.ingest import place_array
    from spark_rapids_ml_tpu.core.membudget import fit_memory_guard

    n, d = x.shape
    # Budgeted admission (core/membudget.py): forest growth has no
    # streaming rung — the binned matrix must be resident — so an
    # over-budget input raises the structured FitMemoryError up front
    # instead of dying inside device_put. row_stats rides along as the
    # sidecar allocation priced on top of the matrix.
    fit_memory_guard(
        "random_forest", x, can_stream=False,
        why_cannot_stream="RandomForest has no streaming fit (histogram "
                          "growth needs the binned matrix resident)",
        mesh=mesh, dtype=np.float32, ledger_families=("rf",),
        extra_bytes=(
            0 if is_device_array(row_stats)
            else np.asarray(row_stats).size * 4
        ),
    )
    n_bins = min(params.getMaxBins(), max(2, n))
    m = resolve_feature_subset(
        params.getFeatureSubsetStrategy(), d, params.getNumTrees(), classification
    )
    key = jax.random.key(params.getSeed())
    k_sample, k_feat = jax.random.split(key)

    # Guarded placement: the whole-dataset uploads go through the
    # ingest.device_put chokepoint (fault point, OOM retry + cache
    # reclaim) instead of bare jnp.asarray calls.
    xj = place_array(x, dtype=jnp.float32)
    w = sample_weights(
        k_sample, params.getNumTrees(), n, params.getSubsamplingRate(),
        params.getBootstrap(),
    )
    # stats_integral: the caller GUARANTEES exact-integer stats (a plain
    # one-hot, no weightCol) — with the 256-clamped bootstrap weights the
    # bf16 exactness is then a static fact and the device-readback
    # predicate (one tunnel round trip per fit) is skipped entirely.
    exact = classification and (
        stats_integral or _hist_exact_in_bf16(row_stats, w)
    )
    kwargs = dict(
        max_depth=params.getMaxDepth(),
        n_bins=n_bins,
        impurity=impurity,
        feat_subset=m,
        min_instances=params.getMinInstancesPerNode(),
        min_info_gain=params.getMinInfoGain(),
        exact_counts=exact,
    )
    rs = place_array(row_stats, dtype=jnp.float32)
    if mesh is not None:
        edges = quantize_features(xj, n_bins)
        xb = bin_features(xj, edges)
        return grow_forest_sharded(
            mesh, xb, rs, w, edges.astype(jnp.float32), k_feat, **kwargs
        )
    return fit_forest_fused(xj, rs, w, k_feat, **kwargs)


class RandomForestClassifier(_RandomForestParams, Estimator, MLReadable):
    """``RandomForestClassifier().setNumTrees(20).fit((X, y))``."""

    # Consumes device (X, y) pairs in place, so tuning loops may feed
    # device-resident fold slices (tuning._device_fold_prep).
    _device_foldable = True

    probabilityCol = Param("_", "probabilityCol", "probability column name", toString)
    rawPredictionCol = Param(
        "_", "rawPredictionCol", "raw prediction column name", toString
    )

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh
        self._setDefault(
            impurity="gini",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
        )

    # Fit-time hint, not a Param (the fitted model's ``numClasses`` is a
    # plain attribute of the same name); survives Params.copy like mesh.
    _declared_num_classes = 0
    _copy_attrs = ("_declared_num_classes",)

    def setMesh(self, mesh) -> "RandomForestClassifier":
        self.mesh = mesh
        return self

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)

    def getNumClasses(self) -> int:
        return self._declared_num_classes

    def setNumClasses(self, v: int):
        """Declare the class count up front — the analogue of Spark ML's
        label-column METADATA (a NominalAttribute's numValues), which
        Spark's RandomForestClassifier trusts WITHOUT rescanning the
        labels. With the hint, a device-resident fit dispatches with no
        label readback at all (inferring the count forces one sync, a
        full round trip under the relay tunnel); like Spark metadata, a
        wrong declaration is the caller's contract violation. 0 restores
        inference."""
        if v != 0 and v < 2:
            raise ValueError(f"numClasses must be 0 (infer) or >= 2, got {v}")
        self._declared_num_classes = int(v)
        return self

    def setProbabilityCol(self, v: str):
        return self._chain(self.probabilityCol, v)

    def setRawPredictionCol(self, v: str):
        return self._chain(self.rawPredictionCol, v)

    def setImpurity(self, v: str):
        if v not in ("gini", "entropy"):
            raise ValueError(f"impurity must be gini or entropy, got {v!r}")
        return self._chain(self.impurity, v)

    def _fit(self, dataset: Any) -> "RandomForestClassificationModel":
        x, y = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        declared = self.getNumClasses()
        if declared:
            if is_device_array(y):
                # Trusted label-metadata path (see setNumClasses): no
                # readback — inferring min/max is the sync the hint
                # exists to avoid.
                y_int = y.ravel().astype(jnp.int32)
            else:
                # Host labels cost nothing to validate, and skipping it
                # let a negative label wrap silently into the LAST class
                # column of the one-hot scatter below (ADVICE r5).
                y_int, _ = validate_int_labels(y)
            n_classes = declared
        else:
            y_int, n_classes = validate_int_labels(y)
            n_classes = max(n_classes, 2)
        w = extract_weights(dataset, self.getWeightCol())
        if is_device_array(y_int):
            # Device labels one-hot on device — no O(n) pull (VERDICT r3 #1).
            row_stats = jax.nn.one_hot(y_int, n_classes, dtype=jnp.float32)
            if w is not None:
                row_stats = row_stats * jnp.asarray(w, dtype=jnp.float32)[:, None]
        else:
            row_stats = np.zeros((y_int.shape[0], n_classes), dtype=np.float32)
            row_stats[np.arange(y_int.shape[0]), y_int] = 1.0  # one-hot counts
            if w is not None:
                # Per-row weights multiply into the stat channels: histogram
                # contributions become weight * count, composing with the
                # per-tree bootstrap weights untouched.
                row_stats *= w[:, None].astype(np.float32)
        with TraceRange("rf-classifier fit", TraceColor.GREEN):
            forest = _fit_forest(
                self, x, row_stats, self.getImpurity(), True, self.mesh,
                stats_integral=w is None,
            )
        model = RandomForestClassificationModel(
            self.uid, forest, numFeatures=x.shape[1], numClasses=n_classes
        )
        return self._copyValues(model)


class RandomForestClassificationModel(_RandomForestParams, Model):
    probabilityCol = RandomForestClassifier.probabilityCol
    rawPredictionCol = RandomForestClassifier.rawPredictionCol

    def __init__(
        self,
        uid: Optional[str] = None,
        forest: Optional[Forest] = None,
        numFeatures: int = 0,
        numClasses: int = 0,
    ):
        super().__init__(uid)
        self._setDefault(
            impurity="gini",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
        )
        self._forest = forest
        self._forest_dev = None
        self.numFeatures = numFeatures
        self.numClasses = numClasses

    def __getstate__(self):
        # Broadcast/pickle ships host forest arrays, never live device
        # buffers; the serving copy rebuilds lazily after load.
        state = dict(self.__dict__)
        state["_forest_dev"] = None
        return state

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)

    @property
    def featureImportances(self) -> np.ndarray:
        return feature_importances(self._forest, self.numFeatures)

    @property
    def totalNumNodes(self) -> int:
        leaf = np.asarray(self._forest.is_leaf)
        feat = np.asarray(self._forest.feature)
        # Reachable nodes: splits plus leaves that carry weight.
        w = np.asarray(self._forest.node_weight)
        return int(np.sum((feat >= 0) | (leaf & (w > 0))))

    def predictProbability(self, x) -> np.ndarray:
        # Shape-bucketed serving path: one AOT tree-routing program per
        # row bucket, forest resident on device across calls.
        return serve_rows(
            _proba_kernel,
            matrix_like(x),
            (_forest_device(self),),
            static={"depth": _forest_depth(self._forest)},
            name="rf.predictProbability",
        )

    def predict(self, x) -> np.ndarray:
        probs = self.predictProbability(x)
        if is_device_array(probs):
            return jnp.argmax(probs, axis=1)
        return np.argmax(probs, axis=1)

    def predictRaw(self, x) -> np.ndarray:
        """Spark RF rawPrediction: unnormalized per-class vote mass (mean
        leaf distribution scaled by the tree count)."""
        return self.predictProbability(x) * self._forest.feature.shape[0]

    def serving_signature(self):
        """The online-serving contract: the tree-routing probability
        kernel, the device-resident forest pytree, and the (n, C)
        class-distribution output spec (float32, the forests' dtype)."""
        n_classes = int(self.numClasses)
        return _forest_signature(
            self,
            _proba_kernel,
            "rf.predictProbability",
            lambda n, dtype: (
                jax.ShapeDtypeStruct((n, n_classes), np.float32),
            ),
            select=_select_argmax,
        )

    def transform(self, dataset: Any) -> Any:
        rows = extract_features(dataset, self.getFeaturesCol(), drop=self.getLabelCol())
        probs = self.predictProbability(rows)
        preds = np.argmax(probs, axis=1)
        # rawPrediction mirrors Spark RF: unnormalized per-class vote mass
        # (mean probability scaled by the tree count).
        raws = probs * len(np.asarray(self._forest.feature))
        if isinstance(dataset, DataFrame):
            out = dataset.withColumn(self.getPredictionCol(), list(preds.astype(float)))
            out = out.withColumn(self.getProbabilityCol(), [p for p in probs])
            return out.withColumn(self.getOrDefault(self.rawPredictionCol), [r for r in raws])
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out = dataset.copy()
                out[self.getPredictionCol()] = preds.astype(float)
                out[self.getProbabilityCol()] = list(probs)
                out[self.getOrDefault(self.rawPredictionCol)] = list(raws)
                return out
        except ImportError:  # pragma: no cover
            pass
        return preds

    def _save_impl(self, path: str) -> None:
        _save_forest_model(
            self,
            path,
            "org.apache.spark.ml.classification.RandomForestClassificationModel",
            {"numFeatures": self.numFeatures, "numClasses": self.numClasses},
        )

    @classmethod
    def _load_impl(cls, path: str) -> "RandomForestClassificationModel":
        metadata, forest = _load_forest_model(path, "RandomForestClassificationModel")
        model = cls(
            metadata["uid"],
            forest,
            numFeatures=metadata.get("numFeatures", 0),
            numClasses=metadata.get("numClasses", 0),
        )
        get_and_set_params(model, metadata)
        return model


class RandomForestRegressor(_RandomForestParams, Estimator, MLReadable):
    """``RandomForestRegressor().setNumTrees(20).fit((X, y))``."""

    # Consumes device (X, y) pairs in place, so tuning loops may feed
    # device-resident fold slices (tuning._device_fold_prep).
    _device_foldable = True

    def __init__(self, uid: Optional[str] = None, mesh=None):
        super().__init__(uid)
        self.mesh = mesh
        self._setDefault(impurity="variance")

    def setMesh(self, mesh) -> "RandomForestRegressor":
        self.mesh = mesh
        return self

    def setImpurity(self, v: str):
        if v != "variance":
            raise ValueError(f"regression impurity must be variance, got {v!r}")
        return self._chain(self.impurity, v)

    def _fit(self, dataset: Any) -> "RandomForestRegressionModel":
        x, y = _extract_xy(dataset, self.getFeaturesCol(), self.getLabelCol())
        # Stats channels [1, y, y^2] -> weighted variance impurity. Labels
        # are centered first: the E[y^2] - mean^2 form in float32 would lose
        # the variance signal to cancellation when |mean(y)| >> std(y);
        # variance gains are shift-invariant, so centering changes nothing
        # but the conditioning. The mean is added back to the leaf values.
        w = extract_weights(dataset, self.getWeightCol())
        if is_device_array(y):
            # Device targets stay resident: mean/center/stack on device
            # (one scalar readback for the leaf-shift constant).
            yj = y.ravel().astype(jnp.float32)
            wj = None if w is None else jnp.asarray(w, dtype=jnp.float32)
            y_mean = float(
                jnp.average(yj, weights=wj) if wj is not None else jnp.mean(yj)
            )
            yc = yj - y_mean
            row_stats = jnp.stack([jnp.ones_like(yc), yc, yc * yc], axis=1)
            if wj is not None:
                row_stats = row_stats * wj[:, None]
        else:
            y_mean = (
                float(np.average(y, weights=w))
                if w is not None
                else (float(np.mean(y)) if y.size else 0.0)
            )
            yc = y - y_mean
            row_stats = np.stack([np.ones_like(yc), yc, yc * yc], axis=1)
            if w is not None:
                row_stats *= w[:, None]
        with TraceRange("rf-regressor fit", TraceColor.GREEN):
            forest = _fit_forest(self, x, row_stats, "variance", False, self.mesh)
        forest = forest._replace(leaf_value=forest.leaf_value + y_mean)
        model = RandomForestRegressionModel(self.uid, forest, numFeatures=x.shape[1])
        return self._copyValues(model)


class RandomForestRegressionModel(_RandomForestParams, Model):
    def __init__(
        self,
        uid: Optional[str] = None,
        forest: Optional[Forest] = None,
        numFeatures: int = 0,
    ):
        super().__init__(uid)
        self._setDefault(impurity="variance")
        self._forest = forest
        self._forest_dev = None
        self.numFeatures = numFeatures

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_forest_dev"] = None
        return state

    @property
    def featureImportances(self) -> np.ndarray:
        return feature_importances(self._forest, self.numFeatures)

    def predict(self, x) -> np.ndarray:
        return serve_rows(
            _reg_kernel,
            matrix_like(x),
            (_forest_device(self),),
            static={"depth": _forest_depth(self._forest)},
            name="rf.predict",
        )

    def serving_signature(self):
        """The online-serving contract: the tree-routing regression
        kernel, the device-resident forest, and the (n,) mean-leaf-value
        output spec (float32, the forests' dtype)."""
        return _forest_signature(
            self,
            _reg_kernel,
            "rf.predict",
            lambda n, dtype: (jax.ShapeDtypeStruct((n,), np.float32),),
        )

    def transform(self, dataset: Any) -> Any:
        rows = extract_features(dataset, self.getFeaturesCol(), drop=self.getLabelCol())
        preds = self.predict(rows)
        if isinstance(dataset, DataFrame):
            return dataset.withColumn(self.getPredictionCol(), list(preds))
        try:
            import pandas as pd

            if isinstance(dataset, pd.DataFrame):
                out = dataset.copy()
                out[self.getPredictionCol()] = preds
                return out
        except ImportError:  # pragma: no cover
            pass
        return preds

    def _save_impl(self, path: str) -> None:
        _save_forest_model(
            self,
            path,
            "org.apache.spark.ml.regression.RandomForestRegressionModel",
            {"numFeatures": self.numFeatures},
        )

    @classmethod
    def _load_impl(cls, path: str) -> "RandomForestRegressionModel":
        metadata, forest = _load_forest_model(path, "RandomForestRegressionModel")
        model = cls(metadata["uid"], forest, numFeatures=metadata.get("numFeatures", 0))
        get_and_set_params(model, metadata)
        return model


def _forest_depth(forest: Forest) -> int:
    """Recover max_depth from the heap size: N = 2^(D+1) - 1."""
    n_nodes = forest.feature.shape[1]
    return int(math.log2(n_nodes + 1)) - 1


def _spark_nodedata_type():
    """Arrow schema of Spark's ``(treeID, nodeData)`` rows — the exact
    DecisionTreeModelReadWrite.NodeData struct (Spark 3.x, incl. the 3.0+
    ``rawCount`` field), so directories written here load in upstream
    Spark and vice versa (SURVEY §3.4 discipline applied to forests)."""
    import pyarrow as pa

    split_t = pa.struct(
        [
            ("featureIndex", pa.int32()),
            ("leftCategoriesOrThreshold", pa.list_(pa.float64())),
            ("numCategories", pa.int32()),
        ]
    )
    node_t = pa.struct(
        [
            ("id", pa.int32()),
            ("prediction", pa.float64()),
            ("impurity", pa.float64()),
            ("impurityStats", pa.list_(pa.float64())),
            ("rawCount", pa.int64()),
            ("gain", pa.float64()),
            ("leftChild", pa.int32()),
            ("rightChild", pa.int32()),
            ("split", split_t),
        ]
    )
    return node_t


def _tree_to_nodedata(f: Forest, t: int, classification: bool) -> list:
    """One tree's heap arrays -> Spark NodeData dicts in PREORDER ids
    (root 0, left subtree next — EnsembleModelReadWrite's numbering).

    Classification ``impurityStats`` are the per-class weighted counts
    (leaf distribution x node weight); regression stats are Spark's
    Variance triplet [count, sum, sumSq] with sumSq reconstructed EXACTLY
    from the stored node impurity (var = sumSq/w - mean^2). Leaves carry
    Spark's sentinels: gain -1, children -1, split (-1, [], -1).

    APPROXIMATION (docs/PARITY.md "Known deviations"): Spark's
    ``rawCount`` is the UNWEIGHTED instance count at the node; the heap
    arrays keep only the weighted node weight, so ``rawCount`` is
    written as ``round(node_weight)``. With no ``weightCol`` (weights
    all 1.0) the two are identical; under fractional row weights the
    stored rawCount is the rounded weighted count, not the row count.
    Predictions are unaffected (nothing reads rawCount back); only the
    persisted field's meaning deviates.
    """
    feature = np.asarray(f.feature[t])
    thr = np.asarray(f.threshold[t], dtype=np.float64)
    leaf = np.asarray(f.is_leaf[t])
    lv = np.asarray(f.leaf_value[t], dtype=np.float64)
    w = np.asarray(f.node_weight[t], dtype=np.float64)
    gain = np.asarray(f.node_gain[t], dtype=np.float64)
    imp = np.asarray(f.node_impurity[t], dtype=np.float64)
    rows: list = []

    def walk(g: int) -> int:
        my = len(rows)
        rows.append(None)
        is_split = (not leaf[g]) and feature[g] >= 0
        if classification:
            stats = (lv[g] * w[g]).tolist()
            pred = float(np.argmax(lv[g]))
        else:
            mean = float(lv[g, 0])
            stats = [w[g], mean * w[g], (imp[g] + mean * mean) * w[g]]
            pred = mean
        node = {
            "id": my,
            "prediction": pred,
            "impurity": float(imp[g]),
            "impurityStats": stats,
            "rawCount": int(round(w[g])),
            "gain": float(gain[g]) if is_split else -1.0,
            "leftChild": -1,
            "rightChild": -1,
            "split": {
                "featureIndex": int(feature[g]) if is_split else -1,
                "leftCategoriesOrThreshold": [float(thr[g])] if is_split else [],
                "numCategories": -1,
            },
        }
        rows[my] = node
        if is_split:
            node["leftChild"] = walk(2 * g + 1)
            node["rightChild"] = walk(2 * g + 2)
        return my

    walk(0)
    return rows


def _save_forest_model(model, path: str, class_name: str, extra: dict) -> None:
    """Spark EnsembleModelReadWrite layout: ``metadata/`` (with
    numFeatures/numClasses/numTrees), ``treesMetadata/`` (one row per tree:
    treeID, per-tree metadata JSON, weight), and ``data/`` as
    ``(treeID, nodeData struct)`` rows in Spark's exact NodeData schema —
    a forest saved here loads in upstream Spark ML and a Spark-written
    forest directory loads here (VERDICT r4 #6)."""
    import json as _json
    import os as _os

    from spark_rapids_ml_tpu.core.persistence import _HAS_ARROW

    f = model._forest
    T = int(np.asarray(f.feature).shape[0])
    classification = "Classification" in class_name
    extra = dict(extra)
    extra.setdefault("numTrees", T)
    save_metadata(model, path, class_name=class_name, extra_metadata=extra)

    if not _HAS_ARROW:  # pragma: no cover - arrow is in every test image
        _np_dir = _os.path.join(path, "data")
        _os.makedirs(_np_dir, exist_ok=True)
        np.savez(
            _os.path.join(_np_dir, "part-00000.npz"),
            **{k: np.asarray(getattr(f, k)) for k in Forest._fields},
        )
        return

    import pyarrow as pa
    import pyarrow.parquet as pq

    node_t = _spark_nodedata_type()
    tree_ids, nodes = [], []
    for t in range(T):
        for nd in _tree_to_nodedata(f, t, classification):
            tree_ids.append(t)
            nodes.append(nd)
    data_dir = _os.path.join(path, "data")
    _os.makedirs(data_dir, exist_ok=True)
    table = pa.Table.from_arrays(
        [
            pa.array(tree_ids, type=pa.int32()),
            pa.array(nodes, type=node_t),
        ],
        schema=pa.schema([("treeID", pa.int32()), ("nodeData", node_t)]),
    )
    pq.write_table(table, _os.path.join(data_dir, "part-00000.parquet"))
    open(_os.path.join(data_dir, "_SUCCESS"), "w").close()

    # treesMetadata: per-tree DefaultParamsWriter metadata + tree weight
    # (all 1.0 — uniform-vote forests, as Spark RF writes).
    tm_dir = _os.path.join(path, "treesMetadata")
    _os.makedirs(tm_dir, exist_ok=True)
    tm = pa.Table.from_arrays(
        [
            pa.array(list(range(T)), type=pa.int32()),
            pa.array(
                [
                    _json.dumps(
                        {
                            "class": (
                                "org.apache.spark.ml.classification."
                                "DecisionTreeClassificationModel"
                                if classification
                                else "org.apache.spark.ml.regression."
                                "DecisionTreeRegressionModel"
                            ),
                            "uid": f"dtc_{model.uid}_{t}",
                            "paramMap": {},
                        }
                    )
                    for t in range(T)
                ],
                type=pa.string(),
            ),
            pa.array([1.0] * T, type=pa.float64()),
        ],
        schema=pa.schema(
            [
                ("treeID", pa.int32()),
                ("metadata", pa.string()),
                ("weights", pa.float64()),
            ]
        ),
    )
    pq.write_table(tm, _os.path.join(tm_dir, "part-00000.parquet"))
    open(_os.path.join(tm_dir, "_SUCCESS"), "w").close()


def _forest_from_nodedata(per_tree: list, classification: bool) -> Forest:
    """Spark ``(treeID, nodeData)`` rows -> heap-indexed Forest arrays.

    Node ids are arbitrary (pointers are explicit in leftChild/rightChild);
    the walk from each tree's root re-derives heap slots. The heap depth is
    the deepest tree's depth (static-shape arrays, as grow_forest builds).
    """

    def node_depth(nodes, nid):
        nd = nodes[nid]
        if nd["leftChild"] < 0:
            return 0
        return 1 + max(
            node_depth(nodes, nd["leftChild"]),
            node_depth(nodes, nd["rightChild"]),
        )

    roots = []
    for nodes in per_tree:
        child_ids = set()
        for nd in nodes.values():
            if nd["leftChild"] >= 0:
                child_ids.add(nd["leftChild"])
                child_ids.add(nd["rightChild"])
        roots.append(next(i for i in nodes if i not in child_ids))

    depth = max(node_depth(nodes, r) for nodes, r in zip(per_tree, roots))
    if depth > 20:
        raise ValueError(f"forest depth {depth} exceeds the supported 20")
    T = len(per_tree)
    N = 2 ** (depth + 1) - 1
    s_out = (
        max(len(nd["impurityStats"]) for nodes in per_tree for nd in nodes.values())
        if classification
        else 1
    )

    feature = np.full((T, N), -1, dtype=np.int32)
    threshold = np.zeros((T, N), dtype=np.float32)
    is_leaf = np.zeros((T, N), dtype=bool)
    leaf_value = np.zeros((T, N, s_out), dtype=np.float32)
    node_weight = np.zeros((T, N), dtype=np.float32)
    node_gain = np.zeros((T, N), dtype=np.float32)
    node_imp = np.zeros((T, N), dtype=np.float32)

    def place(t, nodes, nid, g):
        nd = nodes[nid]
        stats = np.asarray(nd["impurityStats"], dtype=np.float64)
        if classification:
            wsum = float(stats.sum())
            node_weight[t, g] = wsum
            leaf_value[t, g, : stats.size] = (
                stats / wsum if wsum > 0 else 1.0 / stats.size
            )
        else:
            node_weight[t, g] = float(stats[0]) if stats.size else 0.0
            leaf_value[t, g, 0] = nd["prediction"]
        node_imp[t, g] = nd["impurity"]
        if nd["leftChild"] >= 0:
            feature[t, g] = nd["split"]["featureIndex"]
            threshold[t, g] = nd["split"]["leftCategoriesOrThreshold"][0]
            node_gain[t, g] = max(float(nd["gain"]), 0.0)
            place(t, nodes, nd["leftChild"], 2 * g + 1)
            place(t, nodes, nd["rightChild"], 2 * g + 2)
        else:
            is_leaf[t, g] = True

    for t, (nodes, r) in enumerate(zip(per_tree, roots)):
        place(t, nodes, r, 0)

    return Forest(
        jnp.asarray(feature),
        jnp.asarray(threshold),
        jnp.asarray(is_leaf),
        jnp.asarray(leaf_value),
        jnp.asarray(node_weight),
        jnp.asarray(node_gain),
        jnp.asarray(node_imp),
    )


def _load_forest_model(path: str, expected_class: str):
    metadata = load_metadata(path, expected_class=expected_class)
    rows = load_rows(path)
    classification = "Classification" in expected_class
    if "nodeData" in rows:
        by_tree: dict = {}
        for tid, nd in zip(rows["treeID"], rows["nodeData"]):
            by_tree.setdefault(int(tid), {})[int(nd["id"])] = nd
        per_tree = [by_tree[t] for t in sorted(by_tree)]
        return metadata, _forest_from_nodedata(per_tree, classification)
    if "nodeID" in rows:
        # Directories written before the r5 Spark-schema alignment: the
        # flattened (treeID, nodeID, per-field scalar columns) layout.
        # node_impurity was not stored then; it backfills as 0 (only the
        # Spark-format WRITER consumes it, and a legacy model re-saved
        # through it records impurity 0 rather than failing).
        tree_id = np.asarray(rows["treeID"])
        node_id = np.asarray(rows["nodeID"])
        T = int(tree_id.max()) + 1
        N = int(node_id.max()) + 1
        order = np.argsort(tree_id * N + node_id)

        def grid(name, dtype):
            return np.asarray(rows[name])[order].reshape(T, N).astype(dtype)

        leaf_value = np.stack(
            [rows["leafValue"][i] for i in order]
        ).reshape(T, N, -1)
        forest = Forest(
            jnp.asarray(grid("feature", np.int32)),
            jnp.asarray(grid("threshold", np.float32)),
            jnp.asarray(grid("isLeaf", bool)),
            jnp.asarray(leaf_value.astype(np.float32)),
            jnp.asarray(grid("nodeWeight", np.float32)),
            jnp.asarray(grid("nodeGain", np.float32)),
            jnp.zeros((T, N), dtype=jnp.float32),
        )
        return metadata, forest
    # npz fallback written by arrow-less environments: raw heap arrays.
    forest = Forest(*(jnp.asarray(np.asarray(rows[k])) for k in Forest._fields))
    return metadata, forest
