from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix

__all__ = ["RowMatrix"]
