"""Distributed row matrix — the ``RapidsRowMatrix`` equivalent (L3).

Reference: RapidsRowMatrix.scala — rows as RDD[Vector] partitions, covariance
via either per-partition JNI GEMM + Spark reduce (:168-201) or packed
spr/treeAggregate (:202-251), then principal components via driver-side
cuSolver or breeze SVD (:75-125).

Here partitions are dense host blocks (core.data.as_partitions) and covariance
runs per-partition on the accelerator with host-side partial summation (the
Spark-reduce analogue, so the structure generalizes to one-chip-per-executor
deployments), or — when a mesh is supplied — as ONE jitted sharded computation
whose covariance sum rides ICI collectives (parallel.distributed_cov), the
TPU-native fast path SURVEY.md §2 anticipates.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.core.data import (
    as_partitions,
    is_device_array,
    is_streaming_source,
    iter_stream_blocks,
)
from spark_rapids_ml_tpu.ops.covariance import (
    centered_gram,
    centered_gram_packed,
    streaming_mean_and_covariance,
    welford_add_block,
    welford_init,
)
from spark_rapids_ml_tpu.ops.eigh import (
    auto_max_iters,
    eigh_auto,
    eigh_descending,
    eigh_descending_host,
    eigh_topk,
    eigh_topk_host,
    sign_flip,
)
from spark_rapids_ml_tpu.ops.linalg import resolve_precision, triu_to_full
from spark_rapids_ml_tpu.parallel.distributed_cov import distributed_mean_and_covariance
from spark_rapids_ml_tpu.parallel.mesh import shard_rows_from_partitions
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange


from functools import partial as _partial


@_partial(
    jax.jit,
    static_argnames=("k", "center", "precision", "eigen_solver", "eigen_iters"),
)
def _pca_fit_device(x, k, center, precision, eigen_solver, eigen_iters):
    """The whole PCA fit as ONE XLA program on a device-resident array:
    column means + fused centered covariance GEMM + eigensolve + explained
    variance — nothing leaves the device, nothing re-traces across calls
    (module-level jit keyed on shape + the static config). This is the
    path `bench.py` measures through the public estimator API; the
    reference's equivalent spans four JNI calls with host copies between
    each (RapidsRowMatrix.scala:149-257, rapidsml_jni.cu:159-356).
    """
    n, d = x.shape
    mean = jnp.mean(x, axis=0) if center else jnp.zeros((d,), dtype=x.dtype)
    cov = centered_gram(x, mean, precision=precision) / (n - 1)

    def ratio(w, total):
        # Zero-variance input (constant rows) must yield zeros, not NaN —
        # the same `total > 0` guard every host path applies.
        return jnp.where(total > 0, w / jnp.where(total > 0, total, 1), w)

    if eigen_solver == "auto" and k < d:
        w, v, _ = eigh_auto(cov, k, max_iters=auto_max_iters(eigen_iters))
        w = jnp.maximum(w, 0)
        return v, ratio(w, jnp.trace(cov))
    if eigen_solver == "topk" and k < d:
        w, v = eigh_topk(cov, k, iters=eigen_iters)
        w = jnp.maximum(w, 0)
        return v, ratio(w, jnp.trace(cov))
    w, v = eigh_descending(cov)
    w = jnp.maximum(w, 0)
    return v[:, :k], ratio(w, jnp.sum(w))[:k]


class RowMatrix:
    """A row-partitioned matrix with accelerated covariance/PCA.

    Parameters mirror the reference ctor (RapidsRowMatrix.scala:30-45):
    ``mean_centering`` (:36), ``use_gemm`` (:47 — dense fused GEMM vs packed
    spr-layout aggregation), ``use_accel_svd`` (:58 — XLA eigh vs host numpy,
    the cuSolver/breeze switch), ``device_id`` (:70 — chip ordinal, −1 = let
    the runtime pick, replacing TaskContext GPU discovery :171-175).
    """

    def __init__(
        self,
        rows,
        mean_centering: bool = True,
        use_gemm: bool = True,
        use_accel_svd: bool = True,
        device_id: int = -1,
        mesh=None,
        precision: str = "highest",
        dtype=None,
        input_dtype=None,
        backend: str = "xla",
        eigen_solver: str = "full",
        eigen_iters: int = 8,
    ):
        # Streaming sources (block iterators / readers / iterator
        # factories) are never materialized: the covariance runs as a
        # one-pass shifted accumulation at constant memory — the
        # reference's streamed mapPartitions contract
        # (RapidsRowMatrix.scala:170). jax.Array input is the
        # device-resident mode: the whole fit runs as ONE XLA program on
        # the array in place — no host round-trip, no float64 coercion
        # (the input path the reference cannot express: every JNI call
        # copies host arrays, rapidsml_jni.cu:112,179).
        self._device_x = None
        self._num_rows: Optional[int] = None
        self._num_cols: Optional[int] = None
        if is_device_array(rows):
            if rows.ndim != 2:
                raise ValueError(
                    f"device-array input must be 2-D (n, d), got shape {rows.shape}"
                )
            self.partitions: Optional[List[np.ndarray]] = None
            self._stream = None
            self._device_x = rows
            self._num_rows = int(rows.shape[0])
            self._num_cols = int(rows.shape[1])
        elif is_streaming_source(rows):
            self.partitions = None
            self._stream = rows
        else:
            self.partitions = as_partitions(rows)
            self._stream = None
        self.mean_centering = mean_centering
        self.use_gemm = use_gemm
        self.use_accel_svd = use_accel_svd
        self.device_id = device_id
        self.mesh = mesh
        self.precision = self.resolve(
            precision, mesh=mesh, input_dtype=input_dtype, backend=backend
        )
        if self.precision == "dd" and self._device_x is not None:
            raise ValueError(
                "precision='dd' is the host-streaming fp64 emulation; a "
                "device-resident jax.Array is already in its compute dtype "
                "— pass host partitions (or enable x64) for dd semantics"
            )
        if not use_gemm and self._device_x is not None:
            raise ValueError(
                "useGemm=False (the packed spr-layout path) consumes host "
                "partitions; device-resident input runs the fused GEMM "
                "covariance (useGemm=True)"
            )
        if self.precision == "dd" and mesh is not None:
            # dd composes with a mesh ONLY as the per-executor streaming
            # merge (each process runs the dd scan on its local blocks;
            # parallel.distributed.streaming_covariance_process_local) —
            # the GSPMD sharded-gram paths are f32 programs.
            if not (self.partitions is None and jax.process_count() > 1):
                raise ValueError(
                    "precision='dd' with a mesh requires the multi-process "
                    "streaming deployment (per-executor dd scans + moment "
                    "merge); single-process mesh fits use "
                    "precision='highest'"
                )
        # Covariance kernel backend for the GEMM path. Measured on v5e at
        # 1M x 1024 f32/HIGHEST (BASELINE.md): XLA whole-array fusion 24.9
        # TFLOP/s > pallas fused streaming 22.0 > XLA scan-blocked 21.7 —
        # so "xla" is the default and "pallas" is the explicit choice when
        # row blocking is required anyway (it keeps the centered tile and
        # accumulator in VMEM, beating the scan path's HBM round-trip).
        if backend == "pallas":
            # The explicit kernel choice must never be silently dropped:
            # only the materialized single-device GEMM route consults it.
            if mesh is not None:
                raise ValueError("backend='pallas' has no mesh path; use 'xla'")
            if self.partitions is None and self._device_x is None:
                raise ValueError(
                    "backend='pallas' has no streaming path; use 'xla'"
                )
            if not use_gemm:
                raise ValueError(
                    "backend='pallas' applies to the GEMM path (useGemm=True)"
                )
        self.backend = backend
        if eigen_solver not in ("auto", "full", "topk"):
            raise ValueError(
                f"eigen_solver must be 'auto', 'full' or 'topk', got {eigen_solver!r}"
            )
        self.eigen_solver = eigen_solver
        if eigen_iters < 1:
            raise ValueError(f"eigen_iters must be >= 1, got {eigen_iters}")
        self.eigen_iters = int(eigen_iters)
        self._dtype = dtype

    @staticmethod
    def resolve(precision: str, mesh=None, input_dtype=None, backend: str = "xla") -> str:
        """THE home of precision-request resolution (PCA calls this too —
        keep the policy in one place). ``input_dtype`` is the dtype of the
        RAW user container, probed by the caller before as_partitions
        coerced blocks to float64 (core.data.infer_input_dtype). Without
        it, "auto" must not trust partitions[0].dtype (always float64
        post-coercion) — it resolves to "highest" rather than silently
        routing every fit through the slow dd emulation. With a mesh,
        "auto" defers to the mesh covariance path (dd has no mesh route).
        Under ``backend="pallas"`` (an fp32-kernel choice), auto-resolved
        dd yields to "highest"; explicit dd is an error.
        """
        if backend not in ("xla", "pallas"):
            raise ValueError(f"backend must be 'xla' or 'pallas', got {backend!r}")
        if precision == "auto" and mesh is not None:
            return "highest"
        resolved = resolve_precision(precision, input_dtype=input_dtype)
        if backend == "pallas" and resolved == "dd":
            if precision == "dd":
                raise ValueError(
                    "precision='dd' has its own kernels; use backend='xla'"
                )
            return "highest"
        return resolved

    # --- shape (lazy, like numRows/numCols via count()/first(), :48-57) ---

    @property
    def num_rows(self) -> int:
        if self._num_rows is None:
            if self.partitions is None:
                raise RuntimeError(
                    "streaming input: shape is unknown until a fit pass runs"
                )
            self._num_rows = sum(p.shape[0] for p in self.partitions)
        return self._num_rows

    @property
    def num_cols(self) -> int:
        # A fit pass may have recorded the authoritative (global) width —
        # streaming sources discover it then, and multi-process fits must
        # not report a zero-row process's local width.
        if self._num_cols is not None:
            return self._num_cols
        if self.partitions is None:
            raise RuntimeError(
                "streaming input: shape is unknown until a fit pass runs"
            )
        return self.partitions[0].shape[1]

    @property
    def dtype(self):
        if self._dtype is not None:
            return self._dtype
        if self._device_x is not None:
            # Device-resident input computes in ITS dtype — no coercion.
            return self._device_x.dtype
        return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

    def _device(self):
        # local_devices, not devices: under a multi-process gang the global
        # list includes peers' non-addressable chips, and device_put to one
        # of those raises. Identical in single-process runs.
        devices = jax.local_devices()
        if self.device_id >= 0:
            return devices[self.device_id]
        return devices[0]

    # --- column stats (Statistics.colStats analogue, :156) ---

    def column_means(self) -> jnp.ndarray:
        if self._device_x is not None:
            with TraceRange("mean center", TraceColor.ORANGE):
                return jnp.mean(self._device_x, axis=0)
        if self.partitions is None:
            raise RuntimeError(
                "streaming input: column means are computed inside the "
                "one-pass covariance; use compute_covariance()"
            )
        with TraceRange("mean center", TraceColor.ORANGE):
            state = welford_init(self.num_cols, dtype=self.dtype)
            for part in self.partitions:
                state = welford_add_block(state, jnp.asarray(part, dtype=self.dtype))
            return state[1]

    # --- covariance (computeCovariance, :149-257) ---

    def compute_covariance(self) -> jnp.ndarray:
        if self._device_x is not None:
            return self._covariance_device()
        if self.partitions is None:
            return self._covariance_streaming()
        if not (self.mesh is not None and jax.process_count() > 1):
            # Multi-process fits validate the GLOBAL row count inside the
            # mesh path (after the counts allgather): a local pre-check
            # would kill a low-row executor while its peers deadlock in
            # the collective waiting for it.
            n = self.num_rows
            if n < 2:
                raise ValueError(f"need at least 2 rows, got {n}")
        with TraceRange("compute cov", TraceColor.RED):
            if self.mesh is not None:
                return self._covariance_mesh()[1]  # honors mean_centering
            if not self.use_gemm:
                # The explicitly requested packed path outranks auto-dd:
                # with the native runtime it is TRUE fp64 (never less
                # accurate than dd); its no-native fallback routes dd
                # itself when dd precision was resolved.
                return self._covariance_packed()
            if self.precision == "dd":
                return self._covariance_dd()
            mean = (
                self.column_means()
                if self.mean_centering
                else jnp.zeros(self.num_cols, dtype=self.dtype)
            )
            return self._covariance_gemm(mean)

    def _covariance_device(self) -> jnp.ndarray:
        """Covariance of a device-resident array — one fused XLA program,
        no host round-trip (the standalone-covariance sibling of
        :func:`_pca_fit_device`)."""
        x = self._device_array_on_mesh()
        n = self.num_rows
        if n < 2:
            raise ValueError(f"need at least 2 rows, got {n}")
        with TraceRange("compute cov", TraceColor.RED):
            mean = (
                jnp.mean(x, axis=0)
                if self.mean_centering
                else jnp.zeros((self.num_cols,), dtype=x.dtype)
            )
            if self.backend == "pallas":
                from spark_rapids_ml_tpu.ops.pallas.covariance import (
                    centered_gram_pallas,
                )

                interpret = jax.default_backend() != "tpu"
                return centered_gram_pallas(x, mean, interpret=interpret) / (n - 1)
            return centered_gram(x, mean, precision=self.precision) / (n - 1)

    def _device_array_on_mesh(self):
        """The device input honoring a configured mesh: with a mesh set,
        the array is placed row-sharded over the data axis (an explicit
        mesh choice must never be silently dropped — the same stance as
        the pallas guard above), so the fused program runs under GSPMD
        with its covariance psum riding ICI. Without a mesh the array
        computes wherever it lives."""
        x = self._device_x
        if self.mesh is None:
            return x
        from spark_rapids_ml_tpu.parallel.mesh import device_array_rows_on_mesh

        return device_array_rows_on_mesh(x, self.mesh)

    def _covariance_gemm(self, mean: jnp.ndarray) -> jnp.ndarray:
        """Per-partition fused centered Gram + host partial sum (:168-201)."""
        device = self._device()
        acc = None
        use_pallas = self.backend == "pallas"
        if use_pallas:
            from spark_rapids_ml_tpu.ops.pallas.covariance import (
                centered_gram_pallas,
            )

            # The interpreter covers non-TPU platforms (CI's CPU mesh).
            interpret = jax.default_backend() != "tpu"
            if not interpret and np.dtype(self.dtype) == np.float64:
                # Mosaic has no f64 MXU dot — fail clearly instead of at
                # kernel compile (reachable only with x64 forced on TPU).
                raise ValueError(
                    "backend='pallas' compiles f32 kernels; disable x64 or "
                    "pass dtype=jnp.float32 (or use backend='xla')"
                )
        for part in self.partitions:
            with TraceRange("gemm", TraceColor.GREEN):
                blk = jax.device_put(np.asarray(part, dtype=self.dtype), device)
                if use_pallas:
                    gram = centered_gram_pallas(blk, mean, interpret=interpret)
                else:
                    gram = centered_gram(blk, mean, precision=self.precision)
            acc = gram if acc is None else acc + gram
        return acc / (self.num_rows - 1)

    @staticmethod
    def _native_spr_covariance(blocks, center: bool):
        """Stream dense host blocks through the native fp64 Kahan
        accumulator; returns ``(cov fp64 UNCAST, n_rows)``. ONE home for
        the cap/accumulate/finalize sequence shared by the materialized
        packed path and its streaming twin — the uncast return is the
        contract that keeps the fp64 accuracy through the eigensolve on
        no-x64 platforms."""
        from spark_rapids_ml_tpu import native

        acc = None
        for b in blocks:
            if b.shape[0] == 0:
                continue
            if acc is None:
                if b.shape[1] > 65535:
                    raise ValueError(
                        f"packed path caps features at 65535, got {b.shape[1]}"
                    )
                acc = native.SprAccumulator(b.shape[1])
            acc.add_block(b)
        if acc is None:
            raise ValueError("need at least 2 rows to compute a covariance, got 0")
        cov, _ = acc.finalize(center=center)
        return cov, int(acc.n_rows)

    def _covariance_packed(self) -> jnp.ndarray:
        """Packed-upper aggregation path (spr/treeAggregate, :202-251).

        Keeps the reference's n ≤ 65535 wire-format constraint (:66-68).
        When the native host runtime is present, this runs as a true-fp64
        Kahan-compensated streaming accumulation in C++ (the reference's
        all-``double[]`` numerics bar, independent of jax_enable_x64);
        otherwise it falls back to jitted packed Gram accumulation. Both
        compute their own column means in a single pass — no separate
        Welford sweep.
        """
        n_cols = self.num_cols
        if n_cols > 65535:
            raise ValueError(f"packed path caps features at 65535, got {n_cols}")
        from spark_rapids_ml_tpu import native

        if native.available():
            cov, _ = self._native_spr_covariance(
                iter(self.partitions), self.mean_centering
            )
            return cov
        if self.precision == "dd":
            # No native runtime: the packed layout is a compatibility shim
            # here; dd precision still needs the dd kernels.
            return self._covariance_dd()
        mean = (
            self.column_means()
            if self.mean_centering
            else jnp.zeros(n_cols, dtype=self.dtype)
        )
        acc = None
        for part in self.partitions:
            blk = jnp.asarray(part, dtype=self.dtype)
            packed = centered_gram_packed(blk, mean)
            acc = packed if acc is None else acc + packed
        full = triu_to_full(acc)
        return full / (self.num_rows - 1)

    def _covariance_streaming(self) -> jnp.ndarray:
        """Constant-memory covariance over a streaming block source: one
        pass, one block resident at a time (shifted accumulation). Records
        the shape discovered during the pass. With a mesh, each block is
        row-sharded over the data axis and the Gram accumulates replicated
        on device (one psum per block over ICI) — the north-star streamed
        deployment loop (BASELINE config 5)."""
        blocks = iter_stream_blocks(self._stream)
        if self.mesh is not None:
            if jax.process_count() > 1:
                # Executor model: each process streams ITS local blocks on
                # its own chip; one allgather merges the O(d^2) moments —
                # the reference's partition-local compute + cross-process
                # reduce, at constant memory per executor.
                from spark_rapids_ml_tpu.parallel.distributed import (
                    streaming_covariance_process_local,
                )

                with TraceRange("compute cov (stream, multiproc)", TraceColor.RED):
                    # merge="auto": non-dd moments merge as a psum riding
                    # ICI (the mesh is the fabric); dd stays on the exact
                    # fp64 host allgather.
                    _, cov, n = streaming_covariance_process_local(
                        blocks,
                        center=self.mean_centering,
                        dtype=self.dtype,
                        precision=self.precision,
                        mesh=self.mesh,
                    )
                if self.precision == "dd":
                    # Keep the exact-fp64 host covariance — a device-dtype
                    # cast (f32 without x64) would destroy the accuracy
                    # this combination exists to provide.
                    self._num_rows = int(n)
                    self._num_cols = int(cov.shape[0])
                    return cov
            else:
                from spark_rapids_ml_tpu.ops.covariance import (
                    streaming_mean_and_covariance_mesh,
                )

                with TraceRange("compute cov (stream, mesh)", TraceColor.RED):
                    _, cov, n = streaming_mean_and_covariance_mesh(
                        blocks,
                        self.mesh,
                        center=self.mean_centering,
                        dtype=self.dtype,
                        precision=self.precision,
                    )
            self._num_rows = int(n)
            self._num_cols = int(cov.shape[0])
            return jnp.asarray(cov, dtype=self.dtype)
        if not self.use_gemm:
            # Packed-path semantics for streams: the native fp64 Kahan
            # accumulator (tpuml_host.cpp) consumes blocks one at a time —
            # true fp64 at constant memory, the streamed twin of the
            # materialized spr path (RapidsRowMatrix.scala:202-251).
            from spark_rapids_ml_tpu import native

            if native.available():
                with TraceRange("compute cov (stream, native spr)", TraceColor.RED):
                    from spark_rapids_ml_tpu.core.data import _block_to_dense

                    cov, n = self._native_spr_covariance(
                        (_block_to_dense(blk) for blk in blocks),
                        self.mean_centering,
                    )
                self._num_rows = n
                self._num_cols = int(cov.shape[0])
                return cov
            # No native runtime: fall through to the jitted streaming path.
        with TraceRange("compute cov (stream)", TraceColor.RED):
            if self.precision == "dd":
                from spark_rapids_ml_tpu.ops.doubledouble import (
                    covariance_dd_blocks,
                )

                _, cov, n = covariance_dd_blocks(
                    blocks, center=self.mean_centering
                )
                self._num_rows = int(n)
                self._num_cols = int(cov.shape[0])
                # Keep the exact-fp64 host array: casting to the device
                # dtype (fp32 on no-x64 platforms) before the host
                # eigensolve would throw away the dd accuracy.
                return cov
            _, cov, n = streaming_mean_and_covariance(
                blocks,
                center=self.mean_centering,
                dtype=self.dtype,
                precision=self.precision,
            )
        self._num_rows = int(n)
        self._num_cols = int(cov.shape[0])
        return jnp.asarray(cov, dtype=self.dtype)

    def _covariance_dd(self) -> np.ndarray:
        """Double-float fp64-emulated covariance (ops.doubledouble): the
        reference's ``double[]`` numerics (JniRAPIDSML.java:64-69) on fp32
        hardware. ONE streaming pass over the partitions (shifted
        accumulation); fp64 host accumulation of per-block
        extended-precision Gram partials."""
        from spark_rapids_ml_tpu.ops.doubledouble import covariance_dd_blocks

        with TraceRange("dd gemm", TraceColor.GREEN):
            _, cov, _ = covariance_dd_blocks(
                self.partitions, center=self.mean_centering
            )
        return cov

    def _covariance_mesh(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Whole-fit-as-one-XLA-program path over a device mesh.

        Placement is per-shard (shard_rows_from_partitions): the host never
        materializes the concatenated dataset, only one device shard at a
        time. In a multi-process deployment (one process per chip,
        parallel.distributed.initialize), each process contributes its
        LOCAL partitions and the global array is assembled across
        processes — the reference's executor-local partitions + cross-
        process reduce (RapidsRowMatrix.scala:170-201)."""
        import jax as _jax

        if _jax.process_count() > 1:
            from spark_rapids_ml_tpu.parallel.distributed import (
                shard_rows_process_local,
            )

            xs, mask, n_global, d = shard_rows_process_local(
                self.partitions, self.mesh, dtype=np.dtype(self.dtype)
            )
            # Shape facts must be GLOBAL after a distributed placement (a
            # process may hold zero local rows), and the <2 check happens
            # here — consistently on every process, after the allgather.
            # ``d`` is the TRUE width (2-D meshes zero-pad features to the
            # model axis; the padded columns are stripped below).
            self._num_rows = int(n_global)
            self._num_cols = d
            if n_global < 2:
                raise ValueError(f"need at least 2 rows, got {n_global}")
        else:
            d = self.num_cols
            xs, mask, _ = shard_rows_from_partitions(
                self.partitions, self.mesh, dtype=np.dtype(self.dtype)
            )
        mean, cov = distributed_mean_and_covariance(
            xs, mask, self.mesh, precision=self.precision, center=self.mean_centering
        )
        # Strip model-axis feature padding (padded columns are exactly zero).
        return mean[:d], cov[:d, :d]

    # --- PCA (computePrincipalComponentsAndExplainedVariance, :75-125) ---

    def compute_principal_components_and_explained_variance(
        self, k: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        # Validate k before the expensive pass when the shape is known
        # up front. Streaming sources learn d only during the pass, and a
        # multi-process fit only learns the GLOBAL width from the
        # placement allgather (a zero-row executor has no local width).
        if self._device_x is not None and self.use_accel_svd and self.backend != "pallas":
            # Device-resident fused fit: one XLA program end to end.
            n, n_cols = self.num_rows, self.num_cols
            if n < 2:
                raise ValueError(f"need at least 2 rows, got {n}")
            if not 1 <= k <= n_cols:
                raise ValueError(f"k must be in [1, {n_cols}], got {k}")
            with TraceRange("fused device fit", TraceColor.RED):
                u, explained = _pca_fit_device(
                    self._device_array_on_mesh(),
                    k,
                    center=self.mean_centering,
                    precision=self.precision,
                    eigen_solver=self.eigen_solver,
                    eigen_iters=self.eigen_iters,
                )
            return u, explained  # device arrays — the caller decides on host
        shape_known = (
            self.partitions is not None or self._device_x is not None
        ) and not (self.mesh is not None and jax.process_count() > 1)
        if shape_known:
            n_cols = self.num_cols
            if not 1 <= k <= n_cols:
                raise ValueError(f"k must be in [1, {n_cols}], got {k}")
        cov = self.compute_covariance()
        n_cols = self.num_cols
        if not shape_known and not 1 <= k <= n_cols:
            raise ValueError(f"k must be in [1, {n_cols}], got {k}")
        # Host-exact fp64 covariances (dd emulation, or the native Kahan
        # accumulator's packed/streamed paths): a device eigensolve would
        # round them to fp32 on a no-x64 platform — host LAPACK/ARPACK
        # keeps the fp64 accuracy end to end (d x d only, off the critical
        # data path). With x64 on, the device solve is equally exact and
        # keeps useCuSolverSVD semantics.
        host_f64_cov = isinstance(cov, np.ndarray) and cov.dtype == np.float64 and not (
            jax.config.jax_enable_x64
        )
        if self.precision == "dd" or host_f64_cov:
            # An explicit topk request is honored at fp64 via ARPACK
            # rather than silently ignored ("auto" stays with the exact
            # host solve: the fp64 path exists for accuracy, not speed).
            if self.eigen_solver == "topk" and k < n_cols:
                with TraceRange("host fp64 topk", TraceColor.BLUE):
                    w_k, u_k = eigh_topk_host(np.asarray(cov), k)
                    w_k = np.clip(w_k, 0, None)
                    total = float(np.trace(np.asarray(cov)))
                    explained = w_k / total if total > 0 else w_k
                    return u_k, explained
            with TraceRange("host fp64 SVD", TraceColor.BLUE):
                w, u = eigh_descending_host(np.asarray(cov))
        elif self.eigen_solver == "topk" and k < n_cols:
            # Subspace iteration + Rayleigh-Ritz: O(d^2 k) MXU matmuls
            # instead of the full O(d^3) eigensolve — exact explained-
            # variance RATIOS come from the trace, so nothing is lost.
            with TraceRange("topk eigh", TraceColor.BLUE):
                w_k, u_k = eigh_topk(jnp.asarray(cov), k, iters=self.eigen_iters)
                w_k = np.clip(np.asarray(w_k), 0, None)
                total = float(np.trace(np.asarray(cov)))
                explained = w_k / total if total > 0 else w_k
                return np.asarray(u_k), explained
        elif self.eigen_solver == "auto" and k < n_cols and self.use_accel_svd:
            # Self-selecting: subspace iteration that promotes itself to
            # the full eigensolver when the spectrum defeats it (eigh_auto).
            with TraceRange("auto eigh", TraceColor.BLUE):
                w_k, u_k, _ = eigh_auto(
                    jnp.asarray(cov), k, max_iters=auto_max_iters(self.eigen_iters)
                )
                w_k = np.clip(np.asarray(w_k), 0, None)
                total = float(np.trace(np.asarray(cov)))
                explained = w_k / total if total > 0 else w_k
                return np.asarray(u_k), explained
        elif self.use_accel_svd:
            with TraceRange("xla SVD", TraceColor.BLUE):
                w, u = eigh_descending(cov)
                u, w = np.asarray(u), np.asarray(w)
        else:
            with TraceRange("cpu SVD", TraceColor.BLUE):
                # Host LAPACK SVD — the breeze brzSvd analogue (:110-123).
                # For symmetric PSD cov the singular values ARE eigenvalues.
                u, w, _ = np.linalg.svd(np.asarray(cov, dtype=np.float64))
                u = np.asarray(sign_flip(u))
        # Explained variance ratio is eigenvalue-proportional: λ_i / Σλ. The
        # reference normalizes sqrt-eigenvalues (RapidsRowMatrix.scala:101-102
        # via calSVD's seqRoot) — a quirk not copied; the mllib oracle uses λ.
        w = np.clip(w, 0, None)
        total = w.sum()
        explained = w / total if total > 0 else w
        if k < n_cols:
            return u[:, :k], explained[:k]
        return u, explained
