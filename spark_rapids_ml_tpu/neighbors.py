"""Neighbors namespace — parity with the RAPIDS Spark-ML NearestNeighbors."""

from spark_rapids_ml_tpu.models.nearest_neighbors import (
    NearestNeighbors,
    NearestNeighborsModel,
)

__all__ = ["NearestNeighbors", "NearestNeighborsModel"]
