"""Neighbors namespace — parity with the RAPIDS Spark-ML NearestNeighbors."""

from spark_rapids_ml_tpu.models.nearest_neighbors import (
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
)

__all__ = [
    "NearestNeighbors",
    "NearestNeighborsModel",
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
]
