"""ctypes bindings to the native host runtime (libtpuml_host.so).

Loader parity with the reference's ``JniRAPIDSML`` (JniRAPIDSML.java:26-58):
a lazily-initialized per-process singleton that locates the shared library
shipped inside the package directory and binds its C ABI. If the library is
absent, it is built on the fly with the in-tree Makefile when a toolchain is
available; otherwise ``available()`` returns False and callers fall back to
the pure-JAX/numpy paths — the native layer accelerates, never gates.

Surface (native/src/tpuml_host.cpp):
  - SprAccumulator  — fp64 Kahan-compensated streaming covariance
    (packed-upper cublasDspr layout; the reference's spr/treeAggregate path)
  - csr_to_dense    — sparse batch assembly ("concat before cov" hot loop)
  - center_scale    — fused fp64 center + fp32 narrow
  - trace push/pop  — NVTX-parity host ranges
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from spark_rapids_ml_tpu.utils.lockcheck import make_lock

_LIB_NAME = "libtpuml_host.so"
_lock = make_lock("native.loader")
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _package_lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), _LIB_NAME)


def _try_build() -> bool:
    """Build the library from native/ if a toolchain is present."""
    native_dir = os.path.join(os.path.dirname(__file__), "..", "..", "native")
    native_dir = os.path.abspath(native_dir)
    src = os.path.join(native_dir, "src", "tpuml_host.cpp")
    if not os.path.exists(src):
        return False
    try:
        # Direct g++ invocation: faster and fewer moving parts than the CMake
        # path (which remains the documented/official build).
        out = _package_lib_path()
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", out, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(out)
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i8, i32, i64 = ctypes.c_int8, ctypes.c_int32, ctypes.c_int64
    p = ctypes.POINTER
    lib.tpuml_abi_version.restype = i32
    lib.tpuml_spr_create.restype = ctypes.c_void_p
    lib.tpuml_spr_create.argtypes = [i64]
    lib.tpuml_spr_destroy.argtypes = [ctypes.c_void_p]
    lib.tpuml_spr_add_block.restype = i32
    lib.tpuml_spr_add_block.argtypes = [ctypes.c_void_p, p(ctypes.c_double), i64]
    lib.tpuml_spr_merge.restype = i32
    lib.tpuml_spr_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.tpuml_spr_rows.restype = i64
    lib.tpuml_spr_rows.argtypes = [ctypes.c_void_p]
    lib.tpuml_spr_finalize.restype = i32
    lib.tpuml_spr_finalize.argtypes = [
        ctypes.c_void_p,
        p(ctypes.c_double),
        p(ctypes.c_double),
        i32,
    ]
    lib.tpuml_csr_to_dense_f64.restype = i32
    lib.tpuml_csr_to_dense_f64.argtypes = [
        p(i64), p(i32), p(ctypes.c_double), i64, i64, p(ctypes.c_double)
    ]
    lib.tpuml_csr_to_dense_f32.restype = i32
    lib.tpuml_csr_to_dense_f32.argtypes = [
        p(i64), p(i32), p(ctypes.c_double), i64, i64, p(ctypes.c_float)
    ]
    lib.tpuml_center_scale_f32.restype = i32
    lib.tpuml_center_scale_f32.argtypes = [
        p(ctypes.c_double), p(ctypes.c_double), ctypes.c_double, i64, i64,
        p(ctypes.c_float),
    ]
    lib.tpuml_trace_push.argtypes = [ctypes.c_char_p]
    lib.tpuml_trace_pop.argtypes = []
    try:
        _bind_npy(lib)
        lib._tpuml_has_npy = True
    except AttributeError:  # stale library predating the npy loader
        lib._tpuml_has_npy = False
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """Lazily load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        path = _package_lib_path()
        if not os.path.exists(path) and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(path)
            if lib.tpuml_abi_version() != 1:
                return None
            _lib = _bind(lib)
        except OSError:
            return None
        return _lib


def available() -> bool:
    return get_lib() is not None


def _as_c(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class SprAccumulator:
    """fp64 streaming covariance accumulator (native; Kahan-compensated).

    The host-side equivalent of the reference's spr/treeAggregate covariance
    (RapidsRowMatrix.scala:202-251) with true fp64 — the numerics oracle for
    the TPU fp32 paths, and the CPU fallback when no accelerator is present.
    """

    def __init__(self, n_cols: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.tpuml_spr_create(n_cols)
        if not self._handle:
            raise ValueError(f"invalid n_cols {n_cols} (must be 1..65535)")
        self.n_cols = n_cols

    def add_block(self, block: np.ndarray) -> "SprAccumulator":
        block = np.ascontiguousarray(block, dtype=np.float64)
        if block.ndim != 2 or block.shape[1] != self.n_cols:
            raise ValueError(f"block must be (rows, {self.n_cols})")
        rc = self._lib.tpuml_spr_add_block(
            self._handle, _as_c(block, ctypes.c_double), block.shape[0]
        )
        if rc != 0:
            raise RuntimeError(f"spr_add_block failed: {rc}")
        return self

    def merge(self, other: "SprAccumulator") -> "SprAccumulator":
        rc = self._lib.tpuml_spr_merge(self._handle, other._handle)
        if rc != 0:
            raise RuntimeError(f"spr_merge failed: {rc}")
        return self

    @property
    def n_rows(self) -> int:
        return int(self._lib.tpuml_spr_rows(self._handle))

    def finalize(self, center: bool = True):
        """Return (covariance (n, n), column means (n,))."""
        n = self.n_cols
        cov = np.empty((n, n), dtype=np.float64)
        mean = np.empty(n, dtype=np.float64)
        rc = self._lib.tpuml_spr_finalize(
            self._handle,
            _as_c(cov, ctypes.c_double),
            _as_c(mean, ctypes.c_double),
            1 if center else 0,
        )
        if rc == -2:
            raise ValueError(f"need at least 2 rows, got {self.n_rows}")
        if rc != 0:
            raise RuntimeError(f"spr_finalize failed: {rc}")
        return cov, mean

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.tpuml_spr_destroy(handle)
            self._handle = None


def csr_to_dense(indptr, indices, values, n_cols: int, dtype=np.float64) -> np.ndarray:
    """Native CSR -> dense row block ("concat before cov" assembly)."""
    lib = get_lib()
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float64)
    n_rows = indptr.shape[0] - 1
    if lib is None:
        out = np.zeros((n_rows, n_cols), dtype=dtype)
        for r in range(n_rows):
            sl = slice(indptr[r], indptr[r + 1])
            out[r, indices[sl]] = values[sl]
        return out
    if dtype == np.float32:
        out32 = np.empty((n_rows, n_cols), dtype=np.float32)
        rc = lib.tpuml_csr_to_dense_f32(
            _as_c(indptr, ctypes.c_int64), _as_c(indices, ctypes.c_int32),
            _as_c(values, ctypes.c_double), n_rows, n_cols,
            _as_c(out32, ctypes.c_float),
        )
        if rc != 0:
            raise ValueError(f"csr_to_dense failed: {rc} (bad column index?)")
        return out32
    out = np.empty((n_rows, n_cols), dtype=np.float64)
    rc = lib.tpuml_csr_to_dense_f64(
        _as_c(indptr, ctypes.c_int64), _as_c(indices, ctypes.c_int32),
        _as_c(values, ctypes.c_double), n_rows, n_cols,
        _as_c(out, ctypes.c_double),
    )
    if rc != 0:
        raise ValueError(f"csr_to_dense failed: {rc} (bad column index?)")
    return out


def center_scale_f32(x: np.ndarray, mean: np.ndarray, scale: float) -> np.ndarray:
    """Fused (x - mean) * scale with fp64 math, fp32 output."""
    lib = get_lib()
    x = np.ascontiguousarray(x, dtype=np.float64)
    mean = np.ascontiguousarray(mean, dtype=np.float64)
    if lib is None:
        return ((x - mean) * scale).astype(np.float32)
    out = np.empty(x.shape, dtype=np.float32)
    rc = lib.tpuml_center_scale_f32(
        _as_c(x, ctypes.c_double), _as_c(mean, ctypes.c_double),
        float(scale), x.shape[0], x.shape[1], _as_c(out, ctypes.c_float),
    )
    if rc != 0:
        raise RuntimeError(f"center_scale failed: {rc}")
    return out


def trace_push(name: str) -> None:
    lib = get_lib()
    if lib is not None:
        lib.tpuml_trace_push(name.encode())


def trace_pop() -> None:
    lib = get_lib()
    if lib is not None:
        lib.tpuml_trace_pop()


class NpyBlockReader:
    """Streaming block reader over a ``.npy`` file — the native data loader.

    The mmap + madvise readahead lives in C++ (``tpuml_npy_*``): the OS page
    cache double-buffers, :meth:`iter_blocks` warms the NEXT block while
    yielding the current one, and each read is one memcpy out of the
    mapping. Blocks are plain ``(rows, d)`` ndarrays. Pass the READER (or
    its block generator) straight to an estimator for a constant-memory
    fit — one block resident at a time, never the whole file:

        reader = NpyBlockReader("data.npy", block_rows=1 << 20)
        PCA().setK(8).fit(reader)                       # constant memory
        LinearRegression().fit((reader.iter_blocks(), y))
    """

    def __init__(self, path: str, block_rows: int = 1 << 20):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable (no toolchain?)")
        if not getattr(lib, "_tpuml_has_npy", False):
            raise RuntimeError(
                "native library predates the npy loader; rebuild via "
                "`make -C native` (or delete the stale .so)"
            )
        self._lib = lib
        self._handle = lib.tpuml_npy_open(path.encode())
        if not self._handle:
            raise ValueError(
                f"cannot open {path!r}: not a C-order float32/float64 .npy"
            )
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        dtype = ctypes.c_int32()
        lib.tpuml_npy_info(
            self._handle, ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(dtype)
        )
        self.shape = (rows.value, cols.value)
        self.dtype = np.float32 if dtype.value == 0 else np.float64
        self.block_rows = int(block_rows)

    def read_block(self, start: int, n_rows: int) -> np.ndarray:
        n_rows = min(n_rows, self.shape[0] - start)
        out = np.empty((n_rows, self.shape[1]), dtype=self.dtype)
        rc = self._lib.tpuml_npy_read_block(
            self._handle, start, n_rows, out.ctypes.data_as(ctypes.c_void_p)
        )
        if rc != 0:
            raise ValueError(f"read_block({start}, {n_rows}) failed: {rc}")
        return out

    def iter_blocks(self):
        n = self.shape[0]
        b = self.block_rows
        release = getattr(self._lib, "_tpuml_has_npy_release", False)
        for start in range(0, n, b):
            if start + b < n:  # warm the next block while this one is used
                self._lib.tpuml_npy_prefetch(self._handle, start + b, b)
            yield self.read_block(start, b)
            if release and self._handle:
                # The block was memcpy'd out; drop its mapped pages so a
                # full-file pass stays resident-bounded by ~one block.
                self._lib.tpuml_npy_release(self._handle, start, b)

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.tpuml_npy_close(self._handle)
            self._handle = None

    def __enter__(self) -> "NpyBlockReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def _bind_npy(lib: ctypes.CDLL) -> None:
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    lib.tpuml_npy_open.restype = ctypes.c_void_p
    lib.tpuml_npy_open.argtypes = [ctypes.c_char_p]
    lib.tpuml_npy_info.restype = i32
    lib.tpuml_npy_info.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(i64),
        ctypes.POINTER(i64),
        ctypes.POINTER(i32),
    ]
    lib.tpuml_npy_prefetch.restype = i32
    lib.tpuml_npy_prefetch.argtypes = [ctypes.c_void_p, i64, i64]
    lib.tpuml_npy_read_block.restype = i32
    lib.tpuml_npy_read_block.argtypes = [ctypes.c_void_p, i64, i64, ctypes.c_void_p]
    lib.tpuml_npy_close.argtypes = [ctypes.c_void_p]
    try:
        # Added after the first npy ABI shipped; stale builds degrade to
        # streaming without page release rather than losing the loader.
        lib.tpuml_npy_release.restype = i32
        lib.tpuml_npy_release.argtypes = [ctypes.c_void_p, i64, i64]
        lib._tpuml_has_npy_release = True
    except AttributeError:
        lib._tpuml_has_npy_release = False
