"""Graceful degradation — the CPU fallback ladder for single-process fits.

The reference had no such rung: when the GPU was gone, the JNI call threw
and the whole job died (SURVEY §5). For a serving-scale deployment
(ROADMAP north star) the right behavior for SINGLE-PROCESS estimators is
one rung down, not zero: when the accelerator backend is unavailable, or
a recoverable operation exhausts its whole retry budget, finish the fit
on the CPU path and say so loudly — a structured :class:`DegradationWarning`
carrying what failed, why, and what the fallback was.

Gated by ``TPUML_DEGRADE``:

  - ``off`` (default): degradation disabled — errors propagate, classified
    by the retry layer. The safe choice for batch jobs where a silent 50x
    slowdown is worse than a loud failure.
  - ``cpu``: single-process fits fall back to the CPU backend. The right
    choice for serving paths where an answer late beats no answer.

Distributed (mesh / multi-process) fits never degrade — a gang member
quietly switching backends would desynchronize the cohort; those paths
relaunch instead (spark/barrier.py).
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Optional, TypeVar

from spark_rapids_ml_tpu.observability.events import emit
from spark_rapids_ml_tpu.robustness.retry import RetryExhaustedError
from spark_rapids_ml_tpu.utils.envknobs import env_choice

T = TypeVar("T")

DEGRADE_ENV = "TPUML_DEGRADE"
MODES = ("off", "cpu")


class DegradationWarning(UserWarning):
    """Structured record of a degradation event: ``what`` was attempted,
    ``why`` it could not run accelerated, ``fallback`` that served it."""

    def __init__(self, what: str, why: str, fallback: str):
        self.what = what
        self.why = why
        self.fallback = fallback
        super().__init__(
            f"degraded {what}: {why}; continuing on {fallback} "
            f"(set {DEGRADE_ENV}=off to fail instead)"
        )


def degrade_mode() -> str:
    """The active ``TPUML_DEGRADE`` mode (malformed values raise a named
    EnvKnobError, never a silent default)."""
    return env_choice(DEGRADE_ENV, MODES, "off")


def backend_unavailable(exc: BaseException) -> bool:
    """Does this error mean the accelerator BACKEND is gone (vs. a bug)?
    jax surfaces backend-initialization failures as RuntimeErrors with a
    small set of recognizable messages."""
    if not isinstance(exc, RuntimeError):
        return False
    text = str(exc).lower()
    return any(
        marker in text
        for marker in (
            "unable to initialize backend",
            "no visible tpu",
            "failed to initialize",
            "backend 'tpu'",
            "device unavailable",
        )
    )


def cpu_device():
    """The host CPU device, reachable even when the default backend is an
    accelerator (jax keeps the cpu platform registered alongside)."""
    import jax

    return jax.devices("cpu")[0]


def run_degradable(
    accel_fn: Callable[[], T],
    cpu_fn: Callable[[], Any],
    what: str,
    site: Optional[str] = None,
) -> Any:
    """Run ``accel_fn``; on retry exhaustion or backend unavailability,
    either re-raise (mode ``off``) or warn-and-run ``cpu_fn`` (``cpu``).

    Only the two degradable error shapes trigger the fallback — a fatal
    classification (ValueError and friends) propagates untouched in every
    mode, because wrong arguments are wrong on the CPU too.
    """
    try:
        return accel_fn()
    except RetryExhaustedError as exc:
        if degrade_mode() != "cpu":
            raise
        _record_degradation(
            what, f"retry budget exhausted at {site or exc.name}"
        )
        return cpu_fn()
    except RuntimeError as exc:
        if not backend_unavailable(exc) or degrade_mode() != "cpu":
            raise
        _record_degradation(what, f"accelerator backend unavailable ({exc})")
        return cpu_fn()


def _record_degradation(what: str, why: str) -> None:
    """One CPU degradation: the structured warning (unchanged surface), a
    ``degrade`` event-log record, and a counter for dashboards."""
    record_degradation(what, why, "cpu", "the CPU path")


def record_degradation(
    what: str, why: str, fallback: str, fallback_label: Optional[str] = None
) -> None:
    """One degradation of any kind — the shared warn + counter + event
    triple. ``fallback`` is the machine-readable event field (``"cpu"``,
    ``"streaming"``); ``fallback_label`` the human phrasing for the
    warning text (defaults to ``fallback``)."""
    from spark_rapids_ml_tpu.utils.tracing import bump_counter

    warnings.warn(
        DegradationWarning(what, why, fallback_label or fallback),
        stacklevel=4,
    )
    bump_counter("degrade.events")
    emit("degrade", what=what, why=why, fallback=fallback)
