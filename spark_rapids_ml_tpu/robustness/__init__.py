"""Robustness subsystem: deterministic fault injection, the unified
retry/degradation policy, checkpoint/resume for iterative fits, and the
structured warnings they emit.

Four modules, one story (the executable half of docs/PARITY.md "Failure
injection & retry knobs" / "Checkpoint & resume knobs"):

  - :mod:`~spark_rapids_ml_tpu.robustness.faults` — named injection
    sites (``TPUML_FAULTS`` / ``inject(...)``) threaded through every
    layer that can fail, so recovery paths are TESTED code;
  - :mod:`~spark_rapids_ml_tpu.robustness.retry` — the one
    :class:`RetryPolicy` (attempts, backoff + deterministic jitter,
    deadline, retryable-vs-fatal classification) those layers share;
  - :mod:`~spark_rapids_ml_tpu.robustness.degrade` — the
    ``TPUML_DEGRADE``-gated CPU fallback for single-process fits;
  - :mod:`~spark_rapids_ml_tpu.robustness.checkpoint` — segmented-fit
    checkpoint/restore (``TPUML_CHECKPOINT_*``): async atomic solver
    snapshots, validated mid-solve resume, elastic gang restart.
"""

from spark_rapids_ml_tpu.robustness.checkpoint import (
    CheckpointWriteWarning,
    FitCheckpointer,
    data_fingerprint,
    params_hash,
    replicate_state_onto_mesh,
)
from spark_rapids_ml_tpu.robustness.degrade import (
    DegradationWarning,
    degrade_mode,
    run_degradable,
)
from spark_rapids_ml_tpu.robustness.faults import (
    InjectedFault,
    arm,
    disarm,
    fault_point,
    inject,
)
from spark_rapids_ml_tpu.robustness.retry import (
    RetryExhaustedError,
    RetryPolicy,
    classify,
    default_policy,
)

__all__ = [
    "CheckpointWriteWarning",
    "DegradationWarning",
    "FitCheckpointer",
    "InjectedFault",
    "RetryExhaustedError",
    "RetryPolicy",
    "arm",
    "classify",
    "data_fingerprint",
    "default_policy",
    "degrade_mode",
    "disarm",
    "fault_point",
    "inject",
    "params_hash",
    "replicate_state_onto_mesh",
    "run_degradable",
]
