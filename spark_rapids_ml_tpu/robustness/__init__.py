"""Robustness subsystem: deterministic fault injection, the unified
retry/degradation policy, and the structured warnings they emit.

Three modules, one story (the executable half of docs/PARITY.md "Failure
injection & retry knobs"):

  - :mod:`~spark_rapids_ml_tpu.robustness.faults` — named injection
    sites (``TPUML_FAULTS`` / ``inject(...)``) threaded through every
    layer that can fail, so recovery paths are TESTED code;
  - :mod:`~spark_rapids_ml_tpu.robustness.retry` — the one
    :class:`RetryPolicy` (attempts, backoff + deterministic jitter,
    deadline, retryable-vs-fatal classification) those layers share;
  - :mod:`~spark_rapids_ml_tpu.robustness.degrade` — the
    ``TPUML_DEGRADE``-gated CPU fallback for single-process fits.
"""

from spark_rapids_ml_tpu.robustness.degrade import (
    DegradationWarning,
    degrade_mode,
    run_degradable,
)
from spark_rapids_ml_tpu.robustness.faults import (
    InjectedFault,
    arm,
    disarm,
    fault_point,
    inject,
)
from spark_rapids_ml_tpu.robustness.retry import (
    RetryExhaustedError,
    RetryPolicy,
    classify,
    default_policy,
)

__all__ = [
    "DegradationWarning",
    "InjectedFault",
    "RetryExhaustedError",
    "RetryPolicy",
    "arm",
    "classify",
    "default_policy",
    "degrade_mode",
    "disarm",
    "fault_point",
    "inject",
    "run_degradable",
]
