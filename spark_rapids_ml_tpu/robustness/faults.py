"""Deterministic fault injection — named sites, seedless schedules.

The reference never needed this: its failure story was "CUDA throws
through JNI, Spark retries the task" (SURVEY §5), and the recovery path
was exercised only by whatever real hardware happened to do. Here the
recovery paths (retry, gang relaunch, degradation) are first-class code,
so they get a first-class way to be PROVOKED: every layer that can fail
declares a named injection site, and a schedule says which invocations of
that site raise.

Sites (the complete vocabulary — a spec naming anything else is an error):

  - ``ingest.device_put``       host->device placement (core/ingest.py,
                                parallel/mesh.py)
  - ``distributed.initialize``  jax.distributed bring-up
                                (parallel/distributed.py)
  - ``barrier.attempt``         a barrier-stage gang attempt
                                (spark/barrier.py)
  - ``collective.psum``         the cross-process moment merge
                                (parallel/distributed.py)
  - ``persistence.write``       model data write (core/persistence.py)
  - ``checkpoint.write``        one solver-state snapshot write
                                (robustness/checkpoint.py)
  - ``checkpoint.restore``      one checkpoint-file read attempt
                                (robustness/checkpoint.py)
  - ``checkpoint.segment``      the preemption point between solver
                                segments (the segmented-fit drivers)
  - ``solver.segment``          one solver segment / streaming-pass
                                execution (the fit-path OOM chokepoint)
  - ``ipc.send``                one serving-tier frame send
                                (serving/ipc.py — router or member side)
  - ``ipc.recv``                one serving-tier frame receive
                                (serving/ipc.py — a member armed here
                                dies mid-conversation, the router sees a
                                clean EOF)
  - ``member.launch``           spawning one elastic serving member
                                (serving/router.py ``add_member``)
  - ``member.join``             the join replay/warm protocol for one
                                elastic member (serving/router.py)
  - ``refit.ingest``            pulling one batch of fresh rows into a
                                continuous-training cycle
                                (lifecycle/controller.py)
  - ``refit.quality_gate``      scoring candidate vs incumbent on the
                                held-out slice (lifecycle/controller.py)
  - ``refit.swap``              the register → warm → alias-flip tail of
                                a refit cycle (lifecycle/controller.py)
  - ``drift.tick``              one drift-trigger evaluation over the
                                metrics registry (lifecycle/drift.py)

Schedules are counters, not random draws — the same spec always fails the
same invocations, so a chaos test is exactly reproducible:

  - ``site=N``           fail the first N invocations, then succeed
  - ``site=always``      fail every invocation
  - ``site=N@K``         skip the first K invocations, then fail the
                         next N (``always@K``: every invocation from the
                         K-th on) — lets a spawned member handshake and
                         join cleanly, then fail mid-conversation
  - append ``:fatal``    raise a fault classified FATAL (never retried)
  - append ``:torn``     a TORN write: the site is killed mid-file, so a
                         truncated artifact lands at the FINAL path (only
                         ``checkpoint.write`` honors it — the chaos proof
                         that restore rejects corrupt checkpoints)
  - append ``:oom``      a synthetic ``RESOURCE_EXHAUSTED``: the raised
                         fault carries the XLA out-of-memory message
                         marker, so the fit-path OOM recovery (cache
                         reclaim, block halving, streaming fallback)
                         classifies injected and real OOMs identically
  - append ``:stall``    FREEZE instead of raise: the site blocks (in
                         small sleeps, bounded by ``STALL_MAX_S``) until
                         the plan is disarmed or the process is killed —
                         the stuck-but-alive failure mode a socket EOF
                         never models. Exercises the heartbeat-driven
                         stall-retire path (serving/elastic.py)

Specs come from the ``TPUML_FAULTS`` env var (semicolon- or
comma-separated entries, e.g. ``persistence.write=1;barrier.attempt=2``)
or the :func:`inject` context manager. When no plan is active,
:func:`fault_point` is one ``None`` check — zero overhead in production.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

from spark_rapids_ml_tpu.observability.events import emit
from spark_rapids_ml_tpu.utils.envknobs import env_str
from spark_rapids_ml_tpu.utils.lockcheck import make_lock

KNOWN_SITES = frozenset(
    {
        "ingest.device_put",
        "distributed.initialize",
        "barrier.attempt",
        "collective.psum",
        "persistence.write",
        "checkpoint.write",
        "checkpoint.restore",
        "checkpoint.segment",
        "solver.segment",
        "ipc.send",
        "ipc.recv",
        "member.launch",
        "member.join",
        "refit.ingest",
        "refit.quality_gate",
        "refit.swap",
        "drift.tick",
    }
)

# Upper bound on one :stall freeze, so an un-retired stalled process (or
# a test that forgot to kill it) parks for a bounded time, never forever.
STALL_MAX_S = 60.0

ALWAYS = -1  # sentinel count: fail every invocation

FAULTS_ENV = "TPUML_FAULTS"


class InjectedFault(RuntimeError):
    """The error an armed fault site raises. Transient by default (the
    retry layer classifies it retryable); ``fatal=True`` models a
    non-recoverable failure (classified fatal, never retried);
    ``torn=True`` models a kill mid-file — the site that catches it
    leaves a truncated artifact at the final path before re-raising."""

    def __init__(
        self,
        site: str,
        invocation: int,
        fatal: bool = False,
        torn: bool = False,
        oom: bool = False,
    ):
        self.site = site
        self.invocation = invocation
        self.fatal = fatal
        self.torn = torn
        self.oom = oom
        kind = "fatal" if fatal else "transient"
        if torn:
            kind += " torn-write"
        msg = f"injected {kind} fault at site {site!r} (invocation {invocation})"
        if oom:
            # The real XLA message marker, so message-based OOM
            # classification treats injected and real OOMs identically.
            msg = f"RESOURCE_EXHAUSTED: out of memory — {msg}"
        super().__init__(msg)


class Schedule:
    """One site's failure schedule: fail invocations [skip, skip+count)
    — or every invocation from ``skip`` on for ``count=ALWAYS`` —
    raising fatal, transient, or torn faults (or freezing, for stall)."""

    def __init__(
        self,
        count: int,
        fatal: bool = False,
        torn: bool = False,
        oom: bool = False,
        stall: bool = False,
        skip: int = 0,
    ):
        if count != ALWAYS and count < 0:
            raise ValueError(f"schedule count must be >= 0 or ALWAYS, got {count}")
        if skip < 0:
            raise ValueError(f"schedule skip must be >= 0, got {skip}")
        self.count = count
        self.fatal = fatal
        self.torn = torn
        self.oom = oom
        self.stall = stall
        self.skip = skip

    def should_fail(self, invocation: int) -> bool:
        if invocation < self.skip:
            return False
        return self.count == ALWAYS or invocation < self.skip + self.count

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        n = "always" if self.count == ALWAYS else str(self.count)
        if self.skip:
            n += f"@{self.skip}"
        flags = (
            (", fatal" if self.fatal else "")
            + (", torn" if self.torn else "")
            + (", oom" if self.oom else "")
            + (", stall" if self.stall else "")
        )
        return f"Schedule({n}{flags})"


def parse_spec(spec: str) -> Dict[str, Schedule]:
    """Parse a ``TPUML_FAULTS`` spec string into {site: Schedule}."""
    plan: Dict[str, Schedule] = {}
    for entry in spec.replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"malformed fault entry {entry!r}: expected "
                "site=N | site=always, optionally suffixed "
                ":fatal|:torn|:oom|:stall"
            )
        site, _, sched = entry.partition("=")
        site = site.strip()
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r}: known sites are "
                f"{sorted(KNOWN_SITES)}"
            )
        sched = sched.strip()
        fatal = torn = oom = stall = False
        while True:
            if sched.endswith(":fatal"):
                fatal = True
                sched = sched[: -len(":fatal")]
            elif sched.endswith(":torn"):
                torn = True
                sched = sched[: -len(":torn")]
            elif sched.endswith(":oom"):
                oom = True
                sched = sched[: -len(":oom")]
            elif sched.endswith(":stall"):
                stall = True
                sched = sched[: -len(":stall")]
            else:
                break
        skip = 0
        if "@" in sched:
            sched, _, skip_s = sched.partition("@")
            try:
                skip = int(skip_s)
            except ValueError:
                raise ValueError(
                    f"malformed skip offset {skip_s!r} for site {site!r}: "
                    "expected site=N@K with integer K"
                ) from None
            if skip < 0:
                raise ValueError(
                    f"skip offset for site {site!r} must be >= 0, got {skip}"
                )
        if sched == "always":
            count = ALWAYS
        else:
            try:
                count = int(sched)
            except ValueError:
                raise ValueError(
                    f"malformed schedule {sched!r} for site {site!r}: "
                    "expected an integer count or 'always'"
                ) from None
            if count < 0:
                raise ValueError(
                    f"schedule count for site {site!r} must be >= 0, got {count}"
                )
        plan[site] = Schedule(
            count, fatal=fatal, torn=torn, oom=oom, stall=stall, skip=skip
        )
    return plan


class FaultPlan:
    """An active set of schedules plus per-site invocation counters.

    Counters are per-plan (a fresh ``inject`` starts from zero) and
    thread-safe; ``fired`` records every fault actually raised so tests
    can assert the injection really happened."""

    def __init__(self, schedules: Dict[str, Schedule]):
        self._schedules = dict(schedules)
        self._counts: Dict[str, int] = {}  # guarded-by: _lock
        self._lock = make_lock("faults.plan")
        self.fired: List[Tuple[str, int]] = []

    def invocations(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def check(self, site: str) -> None:
        sched = self._schedules.get(site)
        if sched is None:
            return
        with self._lock:
            invocation = self._counts.get(site, 0)
            self._counts[site] = invocation + 1
            if not sched.should_fail(invocation):
                return
            self.fired.append((site, invocation))
            emit("fault", action="fire", site=site, invocation=invocation,
                 fatal=sched.fatal, torn=sched.torn, oom=sched.oom,
                 stall=sched.stall)
        if sched.stall:
            # Freeze OUTSIDE the lock (other sites keep injecting): the
            # stuck-but-alive failure mode. Wakes only when the plan is
            # disarmed/replaced or the bound expires — in the serving
            # tier the stalled member is killed by the heartbeat retire
            # long before either.
            import time

            deadline = time.monotonic() + STALL_MAX_S
            while _active is self and time.monotonic() < deadline:
                time.sleep(0.05)
            return
        raise InjectedFault(
            site, invocation, fatal=sched.fatal, torn=sched.torn,
            oom=sched.oom,
        )


# The active plan. None (the production state) makes fault_point a single
# attribute load + comparison; TPUML_FAULTS arms one at import time so a
# launcher can inject into any process without code changes.
_active: Optional[FaultPlan] = None


def fault_point(site: str) -> None:
    """Declare a named injection site. Raises :class:`InjectedFault` when
    an active plan schedules a failure for this invocation; otherwise a
    no-op."""
    if _active is None:
        return
    _active.check(site)


def active_plan() -> Optional[FaultPlan]:
    return _active


def arm(spec: Union[str, Dict[str, Schedule]]) -> FaultPlan:
    """Install a fault plan (replacing any active one) and return it."""
    global _active
    plan = FaultPlan(parse_spec(spec) if isinstance(spec, str) else spec)
    _active = plan
    emit("fault", action="arm", sites=sorted(plan._schedules))
    return plan


def disarm() -> None:
    global _active
    _active = None
    emit("fault", action="disarm")


class inject:
    """Context manager: arm a plan for the block, restore the previous
    plan (usually none) on exit.

    >>> with inject("persistence.write=1") as plan:
    ...     model.write.overwrite().save(path)   # first write fails, retried
    >>> plan.fired
    [('persistence.write', 0)]
    """

    def __init__(self, spec: Union[str, Dict[str, Schedule]]):
        self._spec = spec
        self._prev: Optional[FaultPlan] = None
        self.plan: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        global _active
        self._prev = _active
        self.plan = arm(self._spec)
        return self.plan

    def __exit__(self, *exc) -> None:
        global _active
        _active = self._prev


def arm_from_env() -> Optional[FaultPlan]:
    """Arm a plan from ``TPUML_FAULTS`` when set (no-op otherwise).
    Runs once at import so a launcher can inject into any process with
    zero code changes; callable again by harnesses that set the env
    after import."""
    spec = env_str(FAULTS_ENV)
    if spec:
        return arm(spec)
    return None


arm_from_env()
