"""Checkpoint/restore for iterative fits — preemption-tolerant solvers.

The reference delegates ALL fault handling to Spark's task retry, which
restarts a failed fit from iteration 0; on preemptible pods that makes a
long Lloyd/L-BFGS/FISTA/UMAP fit effectively un-runnable, because every
solver executes as one jitted ``lax.while_loop`` with no externally
visible intermediate state. This module is the restartable-state half of
the fix (the segmented solvers in ``ops/`` are the other half):

  - **Segmentation** — the ops layer exposes each solver's full state
    (centers/weights, optimizer state, iteration counter, RNG key data,
    convergence scalars) as a pytree between jitted segments of
    ``TPUML_CHECKPOINT_EVERY`` inner iterations. ``0`` (the default)
    keeps the seed's single-program path: same compiles, same perf,
    byte-identical results.
  - **Async atomic snapshots** — :meth:`FitCheckpointer.save_async`
    hands the state pytree to a background thread; the device→host copy
    and the file write happen there, never stalling the next segment's
    dispatch. Files land through the temp-sibling + ``os.replace``
    writer (``core/persistence.py::atomic_file_write``) under
    ``TPUML_CHECKPOINT_DIR``, keyed by estimator uid + param hash.
  - **Validated restore** — :meth:`FitCheckpointer.restore_latest` walks
    checkpoints newest-first, rejecting wrong schema versions, foreign
    param hashes, mismatched data fingerprints, and truncated/corrupt
    files (each rejection falls back to the previous snapshot), and
    resumes mid-solve with bit-identical results.
  - **Counters** — ``checkpoint.write`` / ``checkpoint.restore`` /
    ``checkpoint.skipped_stale`` / ``checkpoint.corrupt`` plus the
    driver-side ``checkpoint.segments`` / ``checkpoint.solver_iters``
    totals ride the ``utils/tracing.py`` registry, so chaos tests assert
    "the resumed fit executed strictly fewer iterations" on counters,
    not log scrapes.

Identity: a checkpoint belongs to (estimator uid, param hash, data
fingerprint). Resuming across processes therefore needs a STABLE uid —
pass one to the estimator constructor (``KMeans(uid="job-42")``), the
way a launcher that resubmits a preempted gang already names its job.

Fault sites: ``checkpoint.write`` (honors ``:torn`` — a kill mid-file
that leaves a truncated artifact at the final path), ``checkpoint.restore``
(one read attempt), and ``checkpoint.segment`` (the preemption point
between segments, where chaos tests kill a fit mid-solve).
"""

from __future__ import annotations

import contextvars
import glob
import hashlib
import io
import json
import os
import shutil
import threading
import warnings
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_ml_tpu.observability.events import (
    current_trace_context,
    emit,
    trace_scope,
)
from spark_rapids_ml_tpu.robustness.faults import InjectedFault, fault_point
from spark_rapids_ml_tpu.utils.envknobs import env_int, env_str
from spark_rapids_ml_tpu.utils.lockcheck import make_lock
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange, bump_counter

SCHEMA_VERSION = 1

# Env knobs (docs/PARITY.md "Checkpoint & resume knobs").
EVERY_ENV = "TPUML_CHECKPOINT_EVERY"
DIR_ENV = "TPUML_CHECKPOINT_DIR"
KEEP_ENV = "TPUML_CHECKPOINT_KEEP"
UMAP_ENV = "TPUML_CHECKPOINT_UMAP"


def checkpoint_every() -> int:
    """Inner iterations per jitted segment; 0 (default) disables
    checkpointing and keeps the monolithic single-program solvers."""
    return env_int(EVERY_ENV, 0, minimum=0)


def checkpoint_dir() -> Optional[str]:
    return env_str(DIR_ENV)


def umap_opt_in() -> bool:
    """UMAP layout checkpointing is opt-in on top of the global knobs:
    its kNN/spectral stages are recomputed (deterministically) on every
    resume, so segmentation only pays off for long epoch schedules."""
    return bool(env_int(UMAP_ENV, 0, minimum=0))


class CheckpointWriteWarning(UserWarning):
    """A snapshot write failed. Checkpointing is best-effort: the fit
    continues (losing at most the failed snapshot's progress window)."""


def params_hash(instance) -> str:
    """Stable hash of an estimator's resolved param map (defaults +
    explicit sets) and class — the "same fit?" half of checkpoint
    identity. maxIter/tol/seed/regParam/... all enter, so a changed
    param can never resume from a foreign solve."""
    merged = {p.name: v for p, v in instance._defaultParamMap.items()}
    merged.update({p.name: v for p, v in instance._paramMap.items()})
    payload = json.dumps(
        {"class": type(instance).__name__, "params": merged},
        sort_keys=True,
        default=repr,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


#: Fixed-point scale for fingerprint quantization: 2**13 keeps dyadic
#: test data exact and any realistic feature magnitude inside int32.
_FP_SCALE = 8192.0


def data_fingerprint(*arrays) -> str:
    """Cheap deterministic fingerprint of the fit inputs — and a
    SHARDING-INVARIANT one, so a gang resumed on a DIFFERENT member
    count recognizes its own checkpoint.

    Per array: trailing dims + dtype (the leading row axis is elided —
    padding to a mesh multiple varies with the member count), then three
    per-column integer moments of the fixed-point-quantized values.
    Integer reductions are associative, so the digest is bit-stable
    under any resharding or reduction order, and zero pad rows
    contribute zero to every moment — two worlds padding the same
    logical rows differently still agree. The moments are
    row-permutation-invariant on purpose: the full-batch solvers
    checkpointed here (Lloyd, L-BFGS, FISTA) are themselves
    row-order-invariant, while any CONTENT change moves a moment.
    A checkpoint from different data must never be resumed: the solver
    state would be valid algebra over the wrong dataset."""
    import jax.numpy as jnp

    h = hashlib.sha256()
    for a in arrays:
        if a is None:
            h.update(b"<none>")
            continue
        a_shape = tuple(getattr(a, "shape", ()))
        h.update(
            repr(("*",) + a_shape[1:] + (str(getattr(a, "dtype", "?")),)).encode()
        )
        if not a_shape:
            h.update(np.asarray(a, dtype=np.float64).tobytes())
            continue
        # Quantize (clip + nan-scrub keeps the float->int conversion
        # defined), then integer column moments — exact on device, O(d)
        # bytes to host, and a global-array reduction works on every
        # process of a multi-controller gang.
        q = jnp.round(
            jnp.nan_to_num(
                jnp.clip(
                    jnp.asarray(a).astype(jnp.float32) * _FP_SCALE,
                    -(2.0 ** 30), 2.0 ** 30,
                )
            )
        ).astype(jnp.int32)
        for moment in (q, q * q, q * q * q):
            col = jnp.sum(moment, axis=0, dtype=jnp.int32)
            h.update(np.asarray(col, dtype=np.int64).tobytes())
    return h.hexdigest()


def _world_size() -> int:
    """The gang's member count as THIS process sees it (1 outside any
    distributed bring-up)."""
    import jax

    try:
        return int(jax.process_count())
    except Exception:  # pragma: no cover - uninitialized backends
        return 1


def _tree_flatten(state) -> Tuple[list, Any]:
    from jax import tree_util

    return tree_util.tree_flatten(state)


def _leaf_compatible(leaf: np.ndarray, template) -> bool:
    """Shape must match exactly; float/bool dtypes must match exactly
    (width IS the numerics contract); integer leaves may differ in WIDTH
    only — an eagerly-built template can carry an int64 counter where
    the jitted segment canonicalized the same weak-typed literal to
    int32 (optax linesearch iteration counts do this), and any-width
    integers restore exactly."""
    if leaf.shape != tuple(np.shape(template)):
        return False
    td = np.dtype(getattr(template, "dtype", type(template)))
    if leaf.dtype == td:
        return True
    return leaf.dtype.kind in "iu" and td.kind in "iu"


class FitCheckpointer:
    """One fit's checkpoint stream: async atomic writes, validated
    newest-first restore, bounded retention.

    Duck-typed surface the segmented solver drivers use:
    ``every`` (segment length), ``restore_latest(template)``,
    ``save_async(step, state)``, ``wait()``, ``finalize_success()``.
    """

    def __init__(
        self,
        run_dir: str,
        uid: str,
        param_hash: str,
        data_fp: str,
        every: int,
        keep: int = 2,
        solver: str = "",
    ):
        self.run_dir = run_dir
        self.uid = uid
        self.param_hash = param_hash
        self.data_fp = data_fp
        self.every = every
        self.keep = keep
        self.solver = solver
        # The solver thread and finalize/wait callers can race over the
        # in-flight writer handle: hand-offs go through one lock.
        self._lock = make_lock("checkpoint.pending")
        self._pending: Optional[threading.Thread] = None  # guarded-by: _lock

    @classmethod
    def for_fit(cls, instance, solver: str, data: Sequence = ()) -> Optional["FitCheckpointer"]:
        """The estimator entry (core/estimator.py): None unless BOTH
        ``TPUML_CHECKPOINT_DIR`` and a positive ``TPUML_CHECKPOINT_EVERY``
        are set — the disabled path must not even compute a fingerprint
        (zero device work, zero extra compiles)."""
        every = checkpoint_every()
        base = checkpoint_dir()
        if every <= 0 or not base:
            return None
        ph = params_hash(instance)
        run_dir = os.path.join(base, f"{instance.uid}-{ph[:12]}")
        return cls(
            run_dir,
            uid=instance.uid,
            param_hash=ph,
            data_fp=data_fingerprint(*data),
            every=every,
            keep=env_int(KEEP_ENV, 2, minimum=1),
            solver=solver,
        )

    # --- restore ---

    def restore_latest(self, template) -> Optional[Tuple[int, Any]]:
        """Newest valid checkpoint as ``(step, state)`` restored into
        ``template``'s pytree structure, or None to start from scratch.

        Validation, newest-first with fallback: schema version, uid,
        param hash, solver, data fingerprint (mismatch → stale, skipped),
        then leaf count/shape/dtype against the template (corrupt or
        foreign state → skipped). A file that cannot even be read —
        truncated by a torn write, or a ``checkpoint.restore`` fault —
        counts as corrupt and falls back to the previous snapshot.
        """
        from jax import tree_util

        t_leaves, treedef = _tree_flatten(template)
        for path in sorted(
            glob.glob(os.path.join(self.run_dir, "ckpt-*.npz")), reverse=True
        ):
            try:
                fault_point("checkpoint.restore")
                with np.load(path, allow_pickle=False) as z:
                    meta = json.loads(str(z["__meta__"][()]))
                    leaves = [z[f"leaf{i}"] for i in range(int(meta["n_leaves"]))]
            except InjectedFault as exc:
                if exc.fatal:
                    raise
                bump_counter("checkpoint.corrupt")
                continue
            except Exception:
                # Truncated zip, missing keys, unreadable JSON — all the
                # shapes a kill mid-write (or bit rot) leaves behind.
                bump_counter("checkpoint.corrupt")
                continue
            if (
                meta.get("schema") != SCHEMA_VERSION
                or meta.get("uid") != self.uid
                or meta.get("param_hash") != self.param_hash
                or meta.get("solver") != self.solver
                or meta.get("data_fingerprint") != self.data_fp
            ):
                bump_counter("checkpoint.skipped_stale")
                continue
            if len(leaves) != len(t_leaves) or not all(
                _leaf_compatible(l, t) for l, t in zip(leaves, t_leaves)
            ):
                bump_counter("checkpoint.skipped_stale")
                continue
            step = int(meta["step"])
            bump_counter("checkpoint.restore")
            bump_counter("checkpoint.restore.steps", step)
            emit("checkpoint", action="restore", step=step, path=path,
                 uid=self.uid, solver=self.solver)
            world_then = meta.get("world")
            world_now = _world_size()
            if world_then is not None and int(world_then) != world_now:
                # The elastic-resume shape: host state restores here,
                # replicate_state_onto_mesh reshards it onto the NEW
                # mesh between segments (the segmented drivers call it).
                bump_counter("checkpoint.gang_resize")
                emit(
                    "gang_resize", action="resume",
                    from_members=int(world_then), to_members=world_now,
                    uid=self.uid, solver=self.solver, step=step,
                )
            return step, tree_util.tree_unflatten(treedef, leaves)
        return None

    # --- save ---

    def save_async(self, step: int, state) -> None:
        """Snapshot ``state`` at ``step`` on a background thread.

        The pytree is flattened on the caller's thread (cheap, no sync);
        the blocking device→host copies, the serialization, and the
        atomic write all happen off-thread, so the solver dispatches its
        next segment immediately. At most one write is in flight —
        ordering is preserved by joining the previous one first (a join
        that only waits when writes are slower than whole segments).

        The writer runs under a COPY of the caller's context, so the
        ambient run scope rides along: the write's span and its
        ``checkpoint`` event carry the fit's ``run_id`` even though they
        land from another thread. The trace hand-off is snapshotted
        EXPLICITLY (events.current_trace_context): the copied contextvar
        only knows the trace root, while the snapshot carries the solver
        span open at save time, so the write span parents to the segment
        that produced the state."""
        leaves, _ = _tree_flatten(state)
        self.wait()
        tc = current_trace_context()
        ctx = contextvars.copy_context()

        def _run():
            with trace_scope(tc):
                self._write(step, leaves)

        t = threading.Thread(target=ctx.run, args=(_run,), daemon=True)
        t.start()
        with self._lock:
            self._pending = t

    def _write(self, step: int, leaves: list) -> None:
        with TraceRange("checkpoint write", TraceColor.ORANGE):
            self._write_inner(step, leaves)

    def _write_inner(self, step: int, leaves: list) -> None:
        from spark_rapids_ml_tpu.core.persistence import atomic_file_write

        final = os.path.join(self.run_dir, f"ckpt-{step:08d}.npz")
        try:
            host = [np.asarray(l) for l in leaves]  # device→host blocks HERE
            meta = {
                "schema": SCHEMA_VERSION,
                "uid": self.uid,
                "param_hash": self.param_hash,
                "data_fingerprint": self.data_fp,
                "solver": self.solver,
                "step": step,
                "n_leaves": len(host),
                # Gang membership at write time: restore compares it to
                # the CURRENT world and flags an elastic resize.
                "world": _world_size(),
            }
            buf = io.BytesIO()
            np.savez(
                buf,
                __meta__=np.asarray(json.dumps(meta)),
                **{f"leaf{i}": a for i, a in enumerate(host)},
            )
            data = buf.getvalue()
            os.makedirs(self.run_dir, exist_ok=True)
            try:
                fault_point("checkpoint.write")
            except InjectedFault as exc:
                if exc.torn:
                    # A kill mid-file: a truncated artifact lands at the
                    # FINAL path (as on a filesystem without atomic
                    # rename) — restore_latest must reject it.
                    with open(final, "wb") as f:
                        f.write(data[: max(1, len(data) // 3)])
                raise
            atomic_file_write(final, data)
            bump_counter("checkpoint.write")
            emit("checkpoint", action="write", step=step, path=final,
                 uid=self.uid, solver=self.solver, bytes=len(data))
            self._prune()
        except BaseException as exc:
            bump_counter("checkpoint.write_failed")
            emit("checkpoint", action="write_failed", step=step,
                 uid=self.uid, error=type(exc).__name__)
            warnings.warn(
                CheckpointWriteWarning(
                    f"checkpoint write for step {step} of {self.uid} failed "
                    f"({type(exc).__name__}: {exc}); the fit continues and "
                    "at most this snapshot's progress window is lost"
                ),
                stacklevel=2,
            )

    def _prune(self) -> None:
        files = sorted(glob.glob(os.path.join(self.run_dir, "ckpt-*.npz")))
        for stale in files[: max(len(files) - self.keep, 0)]:
            try:
                os.remove(stale)
            except OSError:  # pragma: no cover - best-effort retention
                pass

    def wait(self) -> None:
        """Block until the in-flight write (if any) has committed."""
        with self._lock:
            t, self._pending = self._pending, None
        if t is not None:
            t.join()

    def finalize_success(self) -> None:
        """The fit completed: its checkpoints are spent. Flush the last
        write, then drop the run directory so a LATER fit with the same
        identity starts fresh instead of short-circuiting to the old
        converged state."""
        self.wait()
        shutil.rmtree(self.run_dir, ignore_errors=True)
        bump_counter("checkpoint.completed")
        emit("checkpoint", action="finalize", step=-1, uid=self.uid,
             solver=self.solver)


def segment_boundary(checkpointer: Optional["FitCheckpointer"] = None) -> None:
    """The preemption point between solver segments — one named fault
    site shared by every segmented driver, so chaos tests kill a fit
    mid-solve at a deterministic iteration. With a fault plan armed the
    in-flight snapshot is flushed FIRST, so an injected kill lands after
    a known checkpoint committed (deterministic chaos); with no plan —
    production — this is one None check and the write stays async."""
    from spark_rapids_ml_tpu.robustness.faults import active_plan

    if active_plan() is None:
        return
    if checkpointer is not None:
        checkpointer.wait()
    fault_point("checkpoint.segment")


class EphemeralSegmenter:
    """Duck-typed stand-in for :class:`FitCheckpointer` that segments a
    solve WITHOUT touching disk: ``partial_fit``
    (lifecycle/partial_fit.py) routes the solve through the PR 3
    segmented drivers so warm-seed convergence rides the
    ``checkpoint.solver_iters`` counter even when the
    ``TPUML_CHECKPOINT_*`` knobs are unset. ``restore_latest`` is always
    a miss and ``save_async`` a no-op — crash tolerance for a refit
    comes from the lifecycle journal replaying the whole (short) solve,
    not from mid-solve snapshots. Bit-identity with the monolithic
    solver is the PR 3 segmented-equals-monolithic guarantee."""

    def __init__(self, every: int):
        self.every = max(1, int(every))

    def restore_latest(self, template=None):
        return None

    def save_async(self, step, state) -> None:
        pass

    def wait(self) -> None:
        pass

    def finalize_success(self) -> None:
        pass


def replicate_state_onto_mesh(state, mesh):
    """Reshard a host (or single-device) solver-state pytree onto a mesh
    as fully REPLICATED arrays — the elastic-gang-resume placement: a
    relaunched gang restores host state from disk on every process and
    rebuilds the same global arrays its segment programs expect.
    Process-safe: every process contributes its identical host copy."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())

    def place(leaf):
        arr = np.asarray(leaf)
        return jax.make_array_from_process_local_data(sharding, arr, arr.shape)

    return jax.tree_util.tree_map(place, state)
