"""The ONE retry/backoff/classification policy for every recoverable layer.

Before this module, each call site that could fail hand-rolled its own
recovery: the barrier launcher leaned on Spark's opaque stage-attempt
budget, ``distributed.initialize`` trusted jax's heartbeat to surface the
error and hoped the launcher would relaunch, persistence didn't retry at
all. One :class:`RetryPolicy` now owns the decisions all of them share —
how many attempts, how long between them (exponential backoff with
DETERMINISTIC jitter, so two runs of a chaos schedule behave identically),
when the overall deadline has passed, and which errors are even worth
retrying.

Classification is structural, not stringly: programming/usage errors
(``ValueError``/``TypeError``/... ) are FATAL and re-raise immediately
untouched; environmental errors (``OSError``, timeouts, distributed
runtime ``RuntimeError``) are RETRYABLE. An injected fault
(robustness.faults) carries its own classification so chaos tests can
exercise both paths. Exhausting the budget raises
:class:`RetryExhaustedError` with the attempt count and the last error
chained — one classified error, never a hang and never a bare traceback
from deep inside an attempt.

Every attempt runs inside a ``utils/tracing.py`` range
(``retry:<name>#<attempt>``) so recovery is visible in profiles exactly
like the compute it protects — and every attempt/exhaustion bumps the
counter registry (``retry.<name>.attempts`` / ``retry.<name>.exhausted``),
so chaos runs and benchmarks assert on retry counts instead of parsing
logs.
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from spark_rapids_ml_tpu.robustness.faults import InjectedFault
from spark_rapids_ml_tpu.utils.envknobs import env_float, env_int

T = TypeVar("T")

# Env knobs (docs/PARITY.md "Failure injection & retry knobs").
MAX_ATTEMPTS_ENV = "TPUML_RETRY_MAX_ATTEMPTS"
BASE_DELAY_ENV = "TPUML_RETRY_BASE_DELAY"
MAX_DELAY_ENV = "TPUML_RETRY_MAX_DELAY"
DEADLINE_ENV = "TPUML_RETRY_DEADLINE"

# Error types that indicate a bug or a caller mistake, not an environment
# hiccup: retrying cannot help and would only bury the real traceback.
FATAL_TYPES: Tuple[Type[BaseException], ...] = (
    ValueError,
    TypeError,
    KeyError,
    IndexError,
    AttributeError,
    AssertionError,
    NotImplementedError,
)


class RetryExhaustedError(RuntimeError):
    """The retry budget (attempts or deadline) ran out. ``__cause__`` is
    the last underlying error; ``attempts`` how many were made."""

    def __init__(self, name: str, attempts: int, last: BaseException, why: str):
        self.name = name
        self.attempts = attempts
        super().__init__(
            f"{name}: {why} after {attempts} attempt(s); "
            f"last error: {type(last).__name__}: {last}"
        )


def classify(exc: BaseException) -> str:
    """``"retryable"`` or ``"fatal"`` for one raised error."""
    if isinstance(exc, InjectedFault):
        return "fatal" if exc.fatal else "retryable"
    if isinstance(exc, FATAL_TYPES):
        return "fatal"
    # Everything environmental — OSError/ConnectionError/TimeoutError and
    # the distributed-runtime RuntimeErrors (heartbeat loss, coordination
    # service unavailable) — is worth another attempt. That includes
    # device RESOURCE_EXHAUSTED (see :func:`is_oom_error`): retryable
    # because the fit path frees reclaimable memory between attempts.
    return "retryable"


#: Message markers XLA's allocators put in device out-of-memory errors.
#: Injected ``:oom`` faults carry the first marker too, so classification
#: cannot tell (and does not care) whether the OOM was real.
OOM_MARKERS = ("resource_exhausted", "out of memory", "ran out of memory")


def is_oom_error(exc: Optional[BaseException]) -> bool:
    """True when ``exc`` (or anything on its ``__cause__`` chain — a
    :class:`RetryExhaustedError` wraps the last attempt's error) is a
    device out-of-memory failure: an ``XlaRuntimeError`` carrying
    ``RESOURCE_EXHAUSTED``, or an injected ``:oom`` fault. String-matched
    by necessity — jaxlib raises OOM as a plain ``RuntimeError`` subclass
    with no structured code — but only within the RuntimeError subtree,
    so a ValueError mentioning memory never classifies as OOM."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if getattr(exc, "oom", False):
            return True
        if isinstance(exc, RuntimeError):
            text = str(exc).lower()
            if any(marker in text for marker in OOM_MARKERS):
                return True
        exc = exc.__cause__
    return False


def _deterministic_jitter(name: str, attempt: int) -> float:
    """A stable fraction in [0, 1) from (name, attempt) — backoff spreads
    like random jitter but identically on every run and every process, so
    chaos schedules and multi-process cohorts stay in lockstep."""
    digest = hashlib.sha256(f"{name}#{attempt}".encode()).digest()
    return int.from_bytes(digest[:4], "big") / 2**32


class RetryPolicy:
    """max attempts + exponential backoff + deterministic jitter + an
    overall deadline + error classification, as one reusable value.

    ``run(fn, name)`` executes ``fn`` under the policy: fatal errors
    re-raise immediately, retryable ones back off and re-attempt, and an
    exhausted budget raises :class:`RetryExhaustedError` with the last
    error chained.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        deadline: Optional[float] = None,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if base_delay < 0 or max_delay < 0:
            raise ValueError("delays must be >= 0")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.deadline = deadline

    @classmethod
    def from_env(cls, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, deadline: Optional[float] = None) -> "RetryPolicy":
        """The defaults, overridable per process via ``TPUML_RETRY_*``."""
        return cls(
            max_attempts=env_int(MAX_ATTEMPTS_ENV, max_attempts, minimum=1),
            base_delay=env_float(BASE_DELAY_ENV, base_delay, minimum=0.0),
            max_delay=env_float(MAX_DELAY_ENV, max_delay, minimum=0.0),
            deadline=env_float(DEADLINE_ENV, deadline, minimum=0.0),
        )

    def backoff(self, name: str, attempt: int) -> float:
        """Delay before re-attempt ``attempt`` (>= 1): exponential in the
        attempt number, capped, jittered deterministically into
        [0.5x, 1.0x] of the cap so cohort members don't stampede."""
        raw = min(self.base_delay * (2 ** (attempt - 1)), self.max_delay)
        return raw * (0.5 + 0.5 * _deterministic_jitter(name, attempt))

    def run(
        self,
        fn: Callable[[], T],
        name: str,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ) -> T:
        from spark_rapids_ml_tpu.observability.events import emit
        from spark_rapids_ml_tpu.observability.metrics import (
            TIME_BUCKETS,
            histogram,
        )
        from spark_rapids_ml_tpu.utils.tracing import (
            TraceColor,
            TraceRange,
            bump_counter,
        )

        start = time.monotonic()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if (
                self.deadline is not None
                and time.monotonic() - start > self.deadline
            ):
                # last is non-None here: attempt 0 starts before any
                # deadline check can trip (time 0 <= deadline).
                bump_counter(f"retry.{name}.exhausted")
                emit("retry", site=name, attempt=attempt, outcome="exhausted",
                     error=type(last).__name__ if last else None)
                raise RetryExhaustedError(
                    name, attempt, last, f"deadline of {self.deadline}s exceeded"
                ) from last
            try:
                bump_counter(f"retry.{name}.attempts")
                with TraceRange(f"retry:{name}#{attempt}", TraceColor.YELLOW):
                    result = fn()
                emit("retry", site=name, attempt=attempt, outcome="ok")
                return result
            except BaseException as exc:
                if classify(exc) == "fatal":
                    emit("retry", site=name, attempt=attempt, outcome="fatal",
                         error=type(exc).__name__)
                    raise
                last = exc
                if on_retry is not None and attempt + 1 < self.max_attempts:
                    on_retry(attempt, exc)
            delay = self.backoff(name, attempt + 1)
            if attempt + 1 < self.max_attempts:
                histogram(
                    "retry.backoff_seconds",
                    "backoff slept between retry attempts",
                    buckets=TIME_BUCKETS,
                ).observe(delay, site=name)
                emit("retry", site=name, attempt=attempt, outcome="retry",
                     error=type(last).__name__, backoff=delay)
                if delay > 0:
                    time.sleep(delay)
        bump_counter(f"retry.{name}.exhausted")
        emit("retry", site=name, attempt=self.max_attempts, outcome="exhausted",
             error=type(last).__name__ if last else None)
        raise RetryExhaustedError(
            name, self.max_attempts, last, "retry budget exhausted"
        ) from last


def default_policy() -> RetryPolicy:
    """The process-wide policy, re-read from env per call so tests (and
    launchers that tune knobs between stages) see changes immediately."""
    return RetryPolicy.from_env()
