"""Brute-force k-nearest-neighbors kernels — distance GEMM + blocked top-k.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2; the modern RAPIDS Spark-ML line grew a brute-force NearestNeighbors on
cuML). TPU-first design: the pairwise distance matrix is one
(nq, d) x (d, n) GEMM on the MXU — the expansion
||q - x||^2 = ||q||^2 - 2 q.x + ||x||^2 never materializes the (nq, n)
matrix for large item sets; instead items stream through a ``lax.scan`` in
fixed-size blocks with a running (nq, k) top-k merge, so memory is
O(nq * (k + block)) and shapes stay static for XLA.

Distributed: shard items over the mesh data axis with ``shard_map``; each
shard computes its local top-k, then the (nq, k) candidate lists ride ICI
via ``all_gather`` and one final merge selects the global top-k — the
candidate traffic is k/n_items of the naive all-gather of distances.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops.linalg import _dot_precision
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS


def _block_sq_distances(q: jax.Array, xb: jax.Array, q_sq: jax.Array, prec) -> jax.Array:
    """(nq, B) squared euclidean distances of queries to one item block."""
    xb_sq = jnp.sum(xb * xb, axis=1)
    cross = jnp.matmul(q, xb.T, precision=prec)
    d2 = q_sq[:, None] - 2.0 * cross + xb_sq[None, :]
    return jnp.maximum(d2, 0.0)


def _auto_block_items(nq: int, n_items: int) -> int:
    """Item-block size: measured throughput at config 7's shape is flat
    beyond 65536 rows (the knee — 32.5k q/s at 64k vs 32.2k at 256k), so
    cap there; under the cap a ~2 GiB f32 (nq, block) buffer budget
    shrinks blocks for large query batches (memory safety), floored at
    1024 so the scan stays coarse."""
    return min(n_items, 65536, max(1024, (1 << 29) // max(nq, 1)))


@partial(jax.jit, static_argnames=("k", "block_items", "precision", "approx"))
def knn_sq_euclidean(
    queries: jax.Array,
    items: jax.Array,
    k: int,
    item_mask: jax.Array | None = None,
    block_items: int | None = None,
    precision: str = "highest",
    approx: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k by squared euclidean distance — exact by default.

    Returns (distances (nq, k) ascending, indices (nq, k) int32 into
    ``items``). ``item_mask``: 1.0 real / 0.0 padded rows (padded items are
    pushed to +inf so they never surface). Items are processed in
    ``block_items``-row blocks via ``lax.scan``; with fewer items than one
    block the scan has a single step (no penalty).

    ``approx=True`` replaces the per-block exact ``top_k`` with the
    TPU-native ``lax.approx_min_k`` (the PartialReduce op the hardware
    has a fast path for; exact on CPU) while the cross-block candidate
    merge stays exact. This is the TPU-first ANN finding (measured
    numbers in BASELINE.md config 7): a dense MXU scoring pass +
    hardware approximate top-k beats the inverted-list gathers of
    ``ops/ann.ivf_search`` at 1M×96 with ~0.995 recall, because TPU
    gathers are scalarized while the distance GEMM rides the systolic
    array. ``block_items=None`` picks the block from the query count
    (:func:`_auto_block_items` — the estimator path reaches benchmark-
    grade blocks without a knob); pass an explicit value to pin it.
    """
    n_items = items.shape[0]
    if not 1 <= k <= n_items:
        raise ValueError(f"k must be in [1, {n_items}], got {k}")
    if block_items is None:
        block_items = _auto_block_items(queries.shape[0], n_items)
    prec = _dot_precision(precision)
    dtype = queries.dtype
    nq = queries.shape[0]
    q_sq = jnp.sum(queries * queries, axis=1)

    block = min(block_items, n_items)
    n_blocks = -(-n_items // block)
    pad = n_blocks * block - n_items
    items_p = jnp.pad(items, ((0, pad), (0, 0)))
    # With no user mask and no padding, the mask is identically 1 — skip
    # the (nq, block) where-pass entirely (static decision at trace time).
    need_mask = item_mask is not None or pad > 0
    mask_p = jnp.ones(n_items, dtype=dtype) if item_mask is None else item_mask.astype(dtype)
    mask_p = jnp.pad(mask_p, (0, pad))
    item_blocks = items_p.reshape(n_blocks, block, -1)
    mask_blocks = mask_p.reshape(n_blocks, block)

    init_d = jnp.full((nq, k), jnp.inf, dtype=dtype)
    init_i = jnp.full((nq, k), -1, dtype=jnp.int32)

    def step(carry, blk):
        best_d, best_i = carry
        xb, mb, start = blk
        d2 = _block_sq_distances(queries, xb, q_sq, prec)
        if need_mask:
            d2 = jnp.where(mb[None, :] > 0, d2, jnp.inf)
            # Masked (padded) items keep index -1 so that when k exceeds
            # the real item count the unfilled slots surface as (inf, -1)
            # rather than as plausible-looking indices of padding rows.
            idx = jnp.where(mb > 0, start + jnp.arange(block, dtype=jnp.int32), -1)
        else:
            idx = start + jnp.arange(block, dtype=jnp.int32)
        if approx:
            # Hardware partial-reduce narrows the block to k candidates;
            # the candidate merge below stays exact.
            blk_d, blk_pos = lax.approx_min_k(d2, k)
            blk_i = jnp.take_along_axis(
                jnp.broadcast_to(idx, (nq, block)), blk_pos, axis=1
            )
            cand_d = jnp.concatenate([best_d, blk_d], axis=1)
            cand_i = jnp.concatenate([best_i, blk_i], axis=1)
        else:
            cand_d = jnp.concatenate([best_d, d2], axis=1)
            cand_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(idx, (nq, block))], axis=1
            )
        # top_k selects LARGEST; negate for smallest-distance selection.
        neg_top, pos = lax.top_k(-cand_d, k)
        return (-neg_top, jnp.take_along_axis(cand_i, pos, axis=1)), None

    starts = (jnp.arange(n_blocks, dtype=jnp.int32) * block)
    (best_d, best_i), _ = lax.scan(step, (init_d, init_i), (item_blocks, mask_blocks, starts))
    return best_d, best_i


@partial(
    jax.jit, static_argnames=("k", "block_items", "metric", "precision", "approx")
)
def knn(
    queries: jax.Array,
    items: jax.Array,
    k: int,
    item_mask: jax.Array | None = None,
    block_items: int | None = None,
    metric: str = "euclidean",
    precision: str = "highest",
    approx: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k under ``euclidean`` | ``sqeuclidean`` | ``cosine``.

    Cosine distance = 1 - cos(q, x); implemented by L2-normalizing both
    sides, where it reduces to half the squared euclidean distance.
    ``approx`` selects the hardware approximate per-block top-k (see
    :func:`knn_sq_euclidean`).
    """
    if metric not in ("euclidean", "sqeuclidean", "cosine"):
        raise ValueError(f"unknown metric {metric!r}")
    if metric == "cosine":
        qn = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30
        )
        xn = items / jnp.maximum(jnp.linalg.norm(items, axis=1, keepdims=True), 1e-30)
        d2, idx = knn_sq_euclidean(
            qn, xn, k, item_mask, block_items, precision, approx
        )
        return d2 / 2.0, idx
    d2, idx = knn_sq_euclidean(
        queries, items, k, item_mask, block_items, precision, approx
    )
    if metric == "euclidean":
        return jnp.sqrt(d2), idx
    return d2, idx


@partial(jax.jit, static_argnames=("k", "approx", "precision"))
def _merge_block_topk(best_d, best_i, queries, q_sq, xb, start, k,
                      approx: bool, precision: str = "highest"):
    """One streamed-block update of the running (nq, k) top-k state —
    the same candidate-merge math as :func:`knn_sq_euclidean`'s scan step,
    jitted standalone so a HOST loop can drive it block by block."""
    prec = _dot_precision(precision)
    nq = queries.shape[0]
    block = xb.shape[0]
    d2 = _block_sq_distances(queries, xb, q_sq, prec)
    idx = start + jnp.arange(block, dtype=jnp.int32)
    if approx:
        # A block smaller than k (ragged tail, fine-grained sources)
        # cannot be approx-reduced to k candidates — take it whole.
        blk_d, blk_pos = lax.approx_min_k(d2, min(k, block))
        blk_i = jnp.take_along_axis(
            jnp.broadcast_to(idx, (nq, block)), blk_pos, axis=1
        )
        cand_d = jnp.concatenate([best_d, blk_d], axis=1)
        cand_i = jnp.concatenate([best_i, blk_i], axis=1)
    else:
        cand_d = jnp.concatenate([best_d, d2], axis=1)
        cand_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(idx, (nq, block))], axis=1
        )
    neg_top, pos = lax.top_k(-cand_d, k)
    return -neg_top, jnp.take_along_axis(cand_i, pos, axis=1)


def knn_host_streamed(
    queries: jax.Array,
    item_blocks,
    k: int,
    metric: str = "euclidean",
    precision: str = "highest",
    approx: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k against an item set STREAMED from beyond device memory.

    ``item_blocks``: an iterable of host (rows_i, d) blocks (list,
    generator, ``NpyBlockReader.iter_blocks()`` — one pass is enough).
    Each block uploads once, its candidates merge into the running
    (nq, k) state on device (:func:`_merge_block_topk` — the same merge
    discipline as the resident-scan path), and the block's buffers are
    then free: device memory is O(nq*k + block), item capacity is bounded
    by the SOURCE, not HBM (VERDICT r3 #4 — the regime the
    models/approximate_nearest_neighbors docstring used to hand to
    inverted lists on faith). Whether streaming beats a compressed
    resident index (ivfpq) depends on source bandwidth; BASELINE.md
    config 8 records the measured crossover.

    Equal-size blocks reuse one compiled merge; a ragged final block
    compiles once more.
    """
    from spark_rapids_ml_tpu.core.data import _block_to_dense

    if metric not in ("euclidean", "sqeuclidean", "cosine"):
        raise ValueError(f"unknown metric {metric!r}")
    import numpy as np

    q = queries
    if metric == "cosine":
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-30)
    q_sq = jnp.sum(q * q, axis=1)
    nq = q.shape[0]
    dtype = q.dtype
    best_d = jnp.full((nq, k), jnp.inf, dtype=dtype)
    best_i = jnp.full((nq, k), -1, dtype=jnp.int32)
    offset = 0
    np_dtype = np.dtype(dtype)
    for blk in item_blocks:
        b = _block_to_dense(blk, dtype=np_dtype)
        if b.shape[0] == 0:
            continue
        xb = jnp.asarray(b)
        if metric == "cosine":
            xb = xb / jnp.maximum(
                jnp.linalg.norm(xb, axis=1, keepdims=True), 1e-30
            )
        best_d, best_i = _merge_block_topk(
            best_d, best_i, q, q_sq, xb, jnp.int32(offset), k,
            approx=approx, precision=precision,
        )
        offset += b.shape[0]
    if offset < k:
        raise ValueError(f"k={k} exceeds streamed item count {offset}")
    if metric == "euclidean":
        return jnp.sqrt(best_d), best_i
    if metric == "cosine":
        return best_d / 2.0, best_i
    return best_d, best_i


def shard_items(items, mesh, metric: str = "euclidean") -> Tuple[jax.Array, jax.Array]:
    """Place a host (n, d) item matrix on the mesh for :func:`knn_sharded`:
    rows padded up to a multiple of the data axis and sharded P(data),
    features REPLICATED (the model axis contributes nothing to the top-k
    merge, so column-sharding would only buy an implicit all-gather per
    query batch). ``metric="cosine"`` L2-normalizes rows on the host BEFORE
    the upload, so the sharded index is ready for cosine search. Returns
    (items_sharded, item_mask_sharded)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    items = np.asarray(items)
    if metric == "cosine":
        items = items / np.maximum(
            np.linalg.norm(items, axis=1, keepdims=True), 1e-30
        )
    n = items.shape[0]
    dp = mesh.shape[DATA_AXIS]
    n_pad = (-n) % dp
    if n_pad:
        items = np.pad(items, ((0, n_pad), (0, 0)))
    mask = np.zeros(n + n_pad, dtype=items.dtype)
    mask[:n] = 1.0
    xs = jax.device_put(items, NamedSharding(mesh, P(DATA_AXIS)))
    ms = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    return xs, ms


@functools.lru_cache(maxsize=None)
def _sharded_knn_fn(mesh, k: int, n_shard: int, precision: str, approx: bool = False):
    """Build (and cache) the jitted shard_map program for one
    (mesh, k, shard-size, precision) combination — jit's cache is keyed on
    the function object, so the closure must not be rebuilt per call."""
    from spark_rapids_ml_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    prec = _dot_precision(precision)
    k_loc = min(k, n_shard)

    def _local(q, x_blk, m_blk):
        # Local top-k on the full (nq, n_shard) shard distance matrix — the
        # shard already bounds memory (a lax.scan carry would fight
        # shard_map's varying-axis tracking; see test_knn).
        shard_i = lax.axis_index(DATA_AXIS)
        q_sq = jnp.sum(q * q, axis=1)
        d2 = _block_sq_distances(q, x_blk, q_sq, prec)
        d2 = jnp.where(m_blk[None, :] > 0, d2, jnp.inf)
        if approx:
            # Hardware partial-reduce per shard; the all-gathered
            # candidate merge below stays exact (same contract as the
            # single-device approx path in knn_sq_euclidean).
            d_loc, i_loc = lax.approx_min_k(d2, k_loc)
        else:
            neg_top, i_loc = lax.top_k(-d2, k_loc)
            d_loc = -neg_top
        i_glob = i_loc + shard_i * n_shard
        # (n_dev, nq, k) candidates on every device.
        cand_d = lax.all_gather(d_loc, DATA_AXIS)
        cand_i = lax.all_gather(i_glob, DATA_AXIS)
        nq = q.shape[0]
        cand_d = jnp.moveaxis(cand_d, 0, 1).reshape(nq, -1)
        cand_i = jnp.moveaxis(cand_i, 0, 1).reshape(nq, -1)
        neg_top, pos = lax.top_k(-cand_d, k)
        return -neg_top, jnp.take_along_axis(cand_i, pos, axis=1)

    fit = shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()),
        # all_gather leaves values device-varying in the vma system even
        # though every device holds identical candidates; the final top_k is
        # deterministic, so replication holds — skip the static check.
        check_vma=False,
    )
    return jax.jit(fit)


def knn_sharded(
    queries: jax.Array,
    items: jax.Array,
    item_mask: jax.Array,
    mesh,
    k: int,
    precision: str = "highest",
    metric: str = "sqeuclidean",
    approx: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mesh path: items row-sharded P(data) (see :func:`shard_items`),
    queries replicated. ``approx``: hardware approximate per-shard top-k
    (see :func:`knn_sq_euclidean`); the cross-shard merge stays exact.

    Each device computes its shard's local (nq, k) top-k, candidates are
    all-gathered over ICI (k per shard per query — tiny), and one final
    merge picks the global winners. Indices returned are GLOBAL item rows.

    ``metric``: "sqeuclidean" (default, the raw merge quantity) |
    "euclidean" | "cosine". Cosine expects the items to have been sharded
    with ``shard_items(..., metric="cosine")`` (rows pre-normalized);
    queries are normalized here — the same sqeuclidean reduction
    :func:`knn` uses, owned in one place for both call paths.
    """
    if metric not in ("euclidean", "sqeuclidean", "cosine"):
        raise ValueError(f"unknown metric {metric!r}")
    if metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30
        )
    n_shard = items.shape[0] // mesh.shape[DATA_AXIS]
    fn = _sharded_knn_fn(mesh, k, n_shard, precision, approx)
    d2, idx = fn(queries, items, item_mask)
    if metric == "euclidean":
        return jnp.sqrt(d2), idx
    if metric == "cosine":
        return d2 / 2.0, idx
    return d2, idx
