"""Random-forest kernels — level-order histogram tree growth on the MXU.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md §2;
the modern RAPIDS Spark-ML line grew RandomForestClassifier/Regressor on
cuML). The CUDA lineage builds trees node-by-node with scatter-heavy
histogram kernels; the TPU-first formulation instead grows ALL trees and ALL
nodes of one depth level simultaneously with dense one-hot matmuls:

  hist[t, node, feature, bin, stat] =
      sum_r onehot_node[t, r, node] * onehot_bin[r, feature*B + bin]
            * weight[t, r] * row_stat[r, stat]

which is one (T*M, rows) x (rows, d*B) GEMM per stat channel per row block —
exactly the shape the systolic array wants. Rows stream through a
``lax.scan`` in fixed-size blocks so memory stays O(block * d * B) and every
shape is static. Split evaluation (prefix sums over bins, impurity, argmax)
is elementwise/reduction work XLA fuses behind the matmuls.

Trees are heap-indexed, static-shape arrays: node ``g`` has children
``2g+1`` / ``2g+2``; a ``max_depth`` forest always allocates
``2^(max_depth+1)-1`` slots. Prediction walks all trees in parallel with a
``fori_loop`` of gathers — no per-row Python, no recursion, no dynamic
shapes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class Forest(NamedTuple):
    """Heap-indexed forest arrays; N = 2^(max_depth+1) - 1 nodes per tree.

    ``feature`` is -1 at leaves; traversal is governed by ``is_leaf``. A row
    goes LEFT when ``x[feature] <= threshold``. ``leaf_value`` holds the
    class distribution (classification, S=C) or [mean] (regression, S=1).
    ``node_weight``/``node_gain`` feed featureImportances; ``node_impurity``
    is the node's own impurity (gini/entropy/variance), carried so the
    Spark NodeData on-disk format round-trips losslessly (its
    ``impurity``/``impurityStats`` fields — models/random_forest.py).
    """

    feature: jax.Array  # (T, N) int32
    threshold: jax.Array  # (T, N) float32
    is_leaf: jax.Array  # (T, N) bool
    leaf_value: jax.Array  # (T, N, S_out) float32
    node_weight: jax.Array  # (T, N) float32
    node_gain: jax.Array  # (T, N) float32
    node_impurity: jax.Array  # (T, N) float32


def quantize_features(
    x: jax.Array, max_bins: int, max_sample_rows: int = 262_144
) -> jax.Array:
    """Per-feature quantile bin edges, shape (d, max_bins - 1), ascending.

    Continuous-feature binning as in distributed tree learners: edges are
    the (i+1)/B quantiles of (a row-sample of) each feature. Duplicate edges
    from low-cardinality features simply produce empty bins, which can never
    win a split (zero weight on one side).
    """
    n = x.shape[0]
    if n > max_sample_rows:
        stride = -(-n // max_sample_rows)
        x = x[::stride]
    qs = jnp.arange(1, max_bins, dtype=x.dtype) / max_bins
    return jnp.quantile(x, qs, axis=0).T  # (d, B-1)


@jax.jit
def bin_features(x: jax.Array, edges: jax.Array) -> jax.Array:
    """Map raw features to bin ids: bin = #{edges e : x > e}, in [0, B-1].

    With this convention, "bin <= b" is exactly "x <= edges[b]", so raw
    thresholds for prediction are just the winning bin's upper edge.
    """
    # (n, d, B-1) comparison; blocked over rows to bound the temporary.
    n, d = x.shape
    block = max(1, min(n, 1 << 22) // max(1, d * edges.shape[1]) + 1)
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    xp = jnp.pad(x, ((0, pad), (0, 0))).reshape(n_blocks, block, d)

    def step(_, xb):
        return None, jnp.sum(xb[:, :, None] > edges[None, :, :], axis=2)

    _, bins = lax.scan(step, None, xp)
    return bins.reshape(-1, d)[:n].astype(jnp.int32)


def _impurity(stats: jax.Array, kind: str) -> Tuple[jax.Array, jax.Array]:
    """(impurity, total_weight) from a stats vector along the last axis.

    Classification stats = per-class weighted counts; regression stats =
    [w, w*y, w*y^2] (weighted variance impurity, as in Spark's Variance).
    """
    if kind in ("gini", "entropy"):
        w = jnp.sum(stats, axis=-1)
        p = stats / jnp.maximum(w, 1e-12)[..., None]
        if kind == "gini":
            imp = 1.0 - jnp.sum(p * p, axis=-1)
        else:
            # log2, matching Spark ML's Entropy — keeps minInfoGain
            # thresholds comparable across frameworks.
            imp = -jnp.sum(jnp.where(p > 0, p * jnp.log2(p), 0.0), axis=-1)
        return jnp.where(w > 0, imp, 0.0), w
    if kind == "variance":
        w = stats[..., 0]
        mean = stats[..., 1] / jnp.maximum(w, 1e-12)
        var = stats[..., 2] / jnp.maximum(w, 1e-12) - mean * mean
        return jnp.where(w > 0, jnp.maximum(var, 0.0), 0.0), w
    raise ValueError(f"unknown impurity {kind!r}")


def _level_histogram(
    node_idx: jax.Array,  # (T, n) global heap ids, -1 = inactive
    weights: jax.Array,  # (T, n)
    x_binned: jax.Array,  # (n, d)
    row_stats: jax.Array,  # (n, S)
    offset: int,
    n_nodes: int,
    n_bins: int,
    block_rows: int,
    prec=lax.Precision.HIGHEST,
) -> jax.Array:
    """(T, n_nodes, d, n_bins, S) histogram via blocked one-hot GEMMs."""
    T, n = node_idx.shape
    d = x_binned.shape[1]
    S = row_stats.shape[1]
    block = min(block_rows, n)
    n_blocks = -(-n // block)
    pad = n_blocks * block - n

    ni = jnp.pad(node_idx, ((0, 0), (0, pad)), constant_values=-1)
    w = jnp.pad(weights, ((0, 0), (0, pad)))
    xb = jnp.pad(x_binned, ((0, pad), (0, 0)))
    rs = jnp.pad(row_stats, ((0, pad), (0, 0)))

    ni = ni.reshape(T, n_blocks, block).transpose(1, 0, 2)  # (nb, T, bs)
    w = w.reshape(T, n_blocks, block).transpose(1, 0, 2)
    xb = xb.reshape(n_blocks, block, d)
    rs = rs.reshape(n_blocks, block, S)

    def step(hist, blk):
        ni_b, w_b, xb_b, rs_b = blk
        local = ni_b - offset
        in_level = (local >= 0) & (local < n_nodes)
        node_oh = (
            (local[:, :, None] == jnp.arange(n_nodes, dtype=jnp.int32))
            & in_level[:, :, None]
        ).astype(jnp.float32)  # (T, bs, M)
        bin_oh = (
            xb_b[:, :, None] == jnp.arange(n_bins, dtype=jnp.int32)
        ).astype(jnp.float32).reshape(block, d * n_bins)  # (bs, d*B)
        per_s = []
        for s in range(S):
            coef = w_b * rs_b[None, :, s]  # (T, bs)
            a = node_oh * coef[:, :, None]  # (T, bs, M)
            per_s.append(
                jnp.einsum("tbm,bq->tmq", a, bin_oh, precision=prec)
            )
        return hist + jnp.stack(per_s, axis=-1), None

    init = jnp.zeros((T, n_nodes, d * n_bins, S), dtype=jnp.float32)
    hist, _ = lax.scan(step, init, (ni, w, xb, rs))
    return hist.reshape(T, n_nodes, d, n_bins, S)


def _node_totals(
    node_idx: jax.Array,
    weights: jax.Array,
    row_stats: jax.Array,
    offset: int,
    n_nodes: int,
    block_rows: int,
    prec=lax.Precision.HIGHEST,
) -> jax.Array:
    """(T, n_nodes, S) per-node stat totals (no feature/bin split)."""
    T, n = node_idx.shape
    S = row_stats.shape[1]
    block = min(block_rows, n)
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    ni = jnp.pad(node_idx, ((0, 0), (0, pad)), constant_values=-1)
    w = jnp.pad(weights, ((0, 0), (0, pad)))
    rs = jnp.pad(row_stats, ((0, pad), (0, 0)))
    ni = ni.reshape(T, n_blocks, block).transpose(1, 0, 2)
    w = w.reshape(T, n_blocks, block).transpose(1, 0, 2)
    rs = rs.reshape(n_blocks, block, S)

    def step(tot, blk):
        ni_b, w_b, rs_b = blk
        local = ni_b - offset
        in_level = (local >= 0) & (local < n_nodes)
        node_oh = (
            (local[:, :, None] == jnp.arange(n_nodes, dtype=jnp.int32))
            & in_level[:, :, None]
        ).astype(jnp.float32) * w_b[:, :, None]
        return tot + jnp.einsum("tbm,bs->tms", node_oh, rs_b, precision=prec), None

    init = jnp.zeros((T, n_nodes, S), dtype=jnp.float32)
    tot, _ = lax.scan(step, init, (ni, w, rs))
    return tot


@partial(
    jax.jit,
    # `level` stays traced: fold_in takes a traced int, and keeping it out
    # of the program key avoids a per-level retrace on top of the
    # shape-driven one (tpuml-lint: jax-static-loop-arg).
    static_argnames=(
        "impurity", "feat_subset", "min_instances", "min_info_gain"
    ),
)
def split_level(
    hist: jax.Array,  # (T, M, d, B, S) level histogram (already merged)
    key: jax.Array,
    level: int,
    *,
    impurity: str,
    feat_subset: int,
    min_instances: int = 1,
    min_info_gain: float = 0.0,
):
    """Split decision for one tree level from its merged histogram — THE
    single home of split selection: :func:`grow_forest` calls it on
    device-local (psum-merged) histograms, and the pyspark adapter's
    distributed fit calls it on driver-merged executor partials
    (spark/adapter.py), so both deployments decide splits with literally
    the same math (the treeAggregate-then-driver-decide structure of
    RapidsRowMatrix.scala:207-233, applied to trees).

    Returns ``(best_f, best_b, best_gain, split_ok, total, w_parent)``
    with shapes (T, M) / (T, M, S) for total.
    """
    T, m_nodes, d, n_bins, _ = hist.shape
    min_w = float(min_instances)
    left = jnp.cumsum(hist, axis=3)
    total = left[:, :, 0, -1, :]  # (T, M, S): same for every feature
    right = total[:, :, None, None, :] - left
    imp_parent, w_parent = _impurity(total, impurity)  # (T, M)
    imp_l, w_l = _impurity(left, impurity)  # (T, M, d, B)
    imp_r, w_r = _impurity(right, impurity)
    gain = imp_parent[:, :, None, None] - (
        w_l * imp_l + w_r * imp_r
    ) / jnp.maximum(w_parent, 1e-12)[:, :, None, None]

    # Per-node random feature subset: exactly feat_subset features, at
    # zero extra histogram cost (all features were counted anyway).
    if feat_subset < d:
        u = jax.random.uniform(jax.random.fold_in(key, level), (T, m_nodes, d))
        kth = lax.top_k(u, feat_subset)[0][..., -1:]
        f_mask = u >= kth
    else:
        f_mask = jnp.ones((T, m_nodes, d), dtype=bool)

    valid = (
        (w_l >= min_w)
        & (w_r >= min_w)
        & (jnp.arange(n_bins) < n_bins - 1)[None, None, None, :]
        & f_mask[:, :, :, None]
    )
    gain = jnp.where(valid, gain, -jnp.inf)
    flat = gain.reshape(T, m_nodes, d * n_bins)
    best = jnp.argmax(flat, axis=2)
    best_gain = jnp.take_along_axis(flat, best[..., None], axis=2)[..., 0]
    best_f = (best // n_bins).astype(jnp.int32)
    best_b = (best % n_bins).astype(jnp.int32)
    split_ok = (
        (best_gain > 0)
        & (best_gain >= min_info_gain)
        & (w_parent > 0)
    )
    return best_f, best_b, best_gain, split_ok, total, w_parent


def _select_feature(x: jax.Array, f_r: jax.Array) -> jax.Array:
    """out[t, r] = x[r, f_r[t, r]] without a 2-D gather.

    TPU lowers the gather to a scalar loop (~7x slower than this even at
    d = 28); an unrolled where-select streams x once per feature, which the
    fusion turns into d vectorized passes. Falls back to the gather above
    ~256 features, where d passes over (T, n) would cost more.
    """
    d = x.shape[1]
    if d > 256:
        rows = jnp.arange(x.shape[0])
        return jax.vmap(lambda fr: x[rows, fr])(f_r)
    out = jnp.zeros(f_r.shape, x.dtype)
    for f in range(d):
        out = jnp.where(f_r == f, x[:, f][None, :], out)
    return out


def _leaf_prediction(stats: jax.Array, kind: str) -> jax.Array:
    """Per-node prediction from stats: class distribution or [mean]."""
    if kind in ("gini", "entropy"):
        w = jnp.sum(stats, axis=-1, keepdims=True)
        n_cls = stats.shape[-1]
        return jnp.where(w > 0, stats / jnp.maximum(w, 1e-12), 1.0 / n_cls)
    w = stats[..., 0]
    mean = stats[..., 1] / jnp.maximum(w, 1e-12)
    return jnp.where(w > 0, mean, 0.0)[..., None]


@partial(
    jax.jit,
    static_argnames=(
        "max_depth",
        "n_bins",
        "impurity",
        "feat_subset",
        "min_instances",
        "min_info_gain",
        "block_rows",
        "axis_name",
        "exact_counts",
    ),
)
def grow_forest(
    x_binned: jax.Array,  # (n, d) int32
    row_stats: jax.Array,  # (n, S) float32
    weights: jax.Array,  # (T, n) float32 per-tree sample weights
    edges: jax.Array,  # (d, n_bins - 1) float32
    key: jax.Array,
    *,
    max_depth: int,
    n_bins: int,
    impurity: str,
    feat_subset: int,
    min_instances: int = 1,
    min_info_gain: float = 0.0,
    block_rows: int = 4096,
    axis_name: str | None = None,
    exact_counts: bool = True,
) -> Forest:
    """Grow T trees level-synchronously; all shapes static, one XLA program.

    The depth loop is unrolled (max_depth is static and small); each level
    does one blocked-GEMM histogram pass over the data, a fused split
    search, and a gather-based row re-routing — the level-order analogue of
    cuML's node-batched builder, with the MXU doing the counting.

    Distributed mode (``axis_name`` set, under ``shard_map``): rows are
    sharded over the named mesh axis; each device builds its shard's partial
    histogram and one ``psum`` per level merges them over ICI — the Spark
    ``treeAggregate`` of the reference (RapidsRowMatrix.scala:207-233)
    becomes an XLA collective. Split selection then runs identically
    (replicated) on every device, so routing needs no further traffic.
    """
    T, n = weights.shape
    d = x_binned.shape[1]
    S = row_stats.shape[1]
    n_total = 2 ** (max_depth + 1) - 1
    s_out = S if impurity in ("gini", "entropy") else 1
    # Classification histogram entries are small-integer counts (one-hot x
    # Poisson weights <= ~hundreds): EXACT even under one-pass bf16
    # multiplies with fp32 accumulation, so the 6-pass HIGHEST route would
    # buy nothing. Regression stats carry real-valued label channels that
    # bf16 would round at 8 mantissa bits — keep those at HIGHEST. The same
    # rounding hazard applies to classification when a fractional weightCol
    # has been multiplied into row_stats (~2^-9 relative error can flip
    # near-tie splits), so the caller clears ``exact_counts`` in that case.
    hist_prec = (
        lax.Precision.DEFAULT
        if impurity in ("gini", "entropy") and exact_counts
        else lax.Precision.HIGHEST
    )

    feature = jnp.full((T, n_total), -1, dtype=jnp.int32)
    threshold = jnp.zeros((T, n_total), dtype=jnp.float32)
    is_leaf = jnp.zeros((T, n_total), dtype=bool)
    leaf_value = jnp.zeros((T, n_total, s_out), dtype=jnp.float32)
    node_weight = jnp.zeros((T, n_total), dtype=jnp.float32)
    node_gain = jnp.zeros((T, n_total), dtype=jnp.float32)
    node_imp = jnp.zeros((T, n_total), dtype=jnp.float32)

    node_idx = jnp.zeros((T, n), dtype=jnp.int32)  # all rows at the root

    for level in range(max_depth):
        offset = 2**level - 1
        m_nodes = 2**level
        hist = _level_histogram(
            node_idx, weights, x_binned, row_stats, offset, m_nodes, n_bins,
            block_rows, hist_prec,
        )  # (T, M, d, B, S)
        if axis_name is not None:
            hist = lax.psum(hist, axis_name)
        best_f, best_b, best_gain, split_ok, total, w_parent = split_level(
            hist, key, level,
            impurity=impurity, feat_subset=feat_subset,
            min_instances=min_instances, min_info_gain=min_info_gain,
        )

        sl = slice(offset, offset + m_nodes)
        feature = feature.at[:, sl].set(jnp.where(split_ok, best_f, -1))
        threshold = threshold.at[:, sl].set(
            jnp.where(split_ok, edges[best_f, best_b], 0.0)
        )
        is_leaf = is_leaf.at[:, sl].set(~split_ok)
        leaf_value = leaf_value.at[:, sl, :].set(
            _leaf_prediction(total, impurity)
        )
        node_weight = node_weight.at[:, sl].set(w_parent)
        node_gain = node_gain.at[:, sl].set(
            jnp.where(split_ok, best_gain, 0.0)
        )
        node_imp = node_imp.at[:, sl].set(_impurity(total, impurity)[0])

        # Route rows: leaf rows retire (-1); split rows descend. TPU gathers
        # are scalarized and slow (~0.5 s per (T, n) take_along_axis at 2M
        # rows), so the three per-node lookups are PACKED into one int32
        # table gather, and the per-row feature-value lookup becomes an
        # unrolled select over the (static, small) feature axis.
        local = node_idx - offset
        active = (local >= 0) & (local < m_nodes)
        lc = jnp.clip(local, 0, m_nodes - 1)
        packed = best_f * (2 * n_bins) + best_b * 2 + split_ok.astype(jnp.int32)
        packed_r = jnp.take_along_axis(packed, lc, axis=1)  # (T, n): ONE gather
        f_r = packed_r // (2 * n_bins)
        b_r = (packed_r % (2 * n_bins)) // 2
        ok_r = (packed_r % 2) == 1
        xb_r = _select_feature(x_binned, f_r)  # (T, n)
        child = 2 * node_idx + 1 + (xb_r > b_r)
        node_idx = jnp.where(active & ok_r, child, jnp.where(active, -1, node_idx))

    # Bottom level: every surviving node is a leaf.
    offset = 2**max_depth - 1
    m_nodes = 2**max_depth
    total = _node_totals(
        node_idx, weights, row_stats, offset, m_nodes, block_rows, hist_prec
    )
    if axis_name is not None:
        total = lax.psum(total, axis_name)
    sl = slice(offset, offset + m_nodes)
    is_leaf = is_leaf.at[:, sl].set(True)
    leaf_value = leaf_value.at[:, sl, :].set(_leaf_prediction(total, impurity))
    imp_bottom, w_bottom = _impurity(total, impurity)
    node_weight = node_weight.at[:, sl].set(w_bottom)
    node_imp = node_imp.at[:, sl].set(imp_bottom)

    return Forest(
        feature, threshold, is_leaf, leaf_value, node_weight, node_gain, node_imp
    )


@partial(
    jax.jit,
    static_argnames=(
        "max_depth",
        "n_bins",
        "impurity",
        "feat_subset",
        "min_instances",
        "min_info_gain",
        "block_rows",
        "exact_counts",
        "max_sample_rows",
    ),
)
def fit_forest_fused(
    x: jax.Array,  # (n, d) float32 RAW features
    row_stats: jax.Array,  # (n, S) float32
    weights: jax.Array,  # (T, n) float32 per-tree sample weights
    key: jax.Array,
    *,
    max_depth: int,
    n_bins: int,
    impurity: str,
    feat_subset: int,
    min_instances: int = 1,
    min_info_gain: float = 0.0,
    block_rows: int = 4096,
    exact_counts: bool = True,
    max_sample_rows: int = 262_144,
) -> Forest:
    """Whole-fit program: quantile edges + binning + level-order growth in
    ONE XLA executable.

    VERDICT r4 #2: the estimator ran at 38% of its own kernel's rate
    because quantize/bin/one-hot prep lived outside the jitted growth —
    each a separate dispatch through the device tunnel, with the quantile
    sort and binning pass unfused from the histogram scan that re-reads
    the same rows. Compiling the full pipeline as one program removes the
    dispatch gaps and lets XLA schedule the prep against the first level's
    histogram GEMMs. Semantics are identical to quantize_features +
    bin_features + grow_forest called in sequence (same ops, one program).
    """
    edges = quantize_features(x, n_bins, max_sample_rows)
    xb = bin_features(x, edges)
    return grow_forest(
        xb,
        row_stats,
        weights,
        edges.astype(jnp.float32),
        key,
        max_depth=max_depth,
        n_bins=n_bins,
        impurity=impurity,
        feat_subset=feat_subset,
        min_instances=min_instances,
        min_info_gain=min_info_gain,
        block_rows=block_rows,
        exact_counts=exact_counts,
    )


def grow_forest_sharded(
    mesh,
    x_binned: jax.Array,
    row_stats: jax.Array,
    weights: jax.Array,
    edges: jax.Array,
    key: jax.Array,
    **kwargs,
) -> Forest:
    """Mesh path: rows sharded over the data axis, per-shard partial
    histograms merged with one ``psum`` per level (see :func:`grow_forest`).

    Inputs are HOST arrays; rows are padded to a multiple of the data-axis
    size with zero weight (padded rows contribute nothing to any histogram).
    The returned forest is replicated — identical on every device.
    """
    from spark_rapids_ml_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    n = x_binned.shape[0]
    dp = mesh.shape[DATA_AXIS]
    pad = (-n) % dp
    if pad:
        x_binned = jnp.concatenate(
            [x_binned, jnp.zeros((pad, x_binned.shape[1]), x_binned.dtype)]
        )
        row_stats = jnp.concatenate(
            [row_stats, jnp.zeros((pad, row_stats.shape[1]), row_stats.dtype)]
        )
        weights = jnp.concatenate(
            [weights, jnp.zeros((weights.shape[0], pad), weights.dtype)], axis=1
        )

    def local(xb, rs, w, e, k):
        return grow_forest(xb, rs, w, e, k, axis_name=DATA_AXIS, **kwargs)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS), P(), P()),
        out_specs=Forest(P(), P(), P(), P(), P(), P(), P()),
        # psum'd histograms make every split decision replicated; the vma
        # checker cannot see that, so skip the static check (as in ops.knn).
        check_vma=False,
    )
    return fn(x_binned, row_stats, weights, edges, key)


@partial(jax.jit, static_argnames=("max_depth",))
def forest_apply(
    x: jax.Array, forest: Forest, max_depth: int
) -> jax.Array:
    """Leaf index per (tree, row): parallel root-to-leaf walk, (T, n) int32.

    Per step: feature id and leaf flag ride ONE packed int gather (TPU
    gathers are scalarized — see the routing note in :func:`grow_forest`),
    the threshold a second; the feature value is an unrolled select.
    """
    idx = jnp.zeros((forest.feature.shape[0], x.shape[0]), dtype=jnp.int32)
    packed = jnp.maximum(forest.feature, 0) * 2 + forest.is_leaf.astype(jnp.int32)

    def body(_, idx):
        p = jnp.take_along_axis(packed, idx, axis=1)
        f = p // 2
        leaf = (p % 2) == 1
        thr = jnp.take_along_axis(forest.threshold, idx, axis=1)
        xv = _select_feature(x, f)
        child = 2 * idx + 1 + (xv > thr)
        return jnp.where(leaf, idx, child.astype(jnp.int32))

    return lax.fori_loop(0, max_depth, body, idx)


@partial(jax.jit, static_argnames=("max_depth",))
def forest_predict_proba(x: jax.Array, forest: Forest, max_depth: int) -> jax.Array:
    """(n, C) mean of per-tree leaf class distributions.

    Gathered one class at a time: a (T, n, C) take_along_axis would tile-pad
    the tiny class axis to the 128-lane register width on TPU (a 64x memory
    blowup at C=2 — 20 GB at 2M rows x 20 trees).
    """
    idx = forest_apply(x, forest, max_depth)  # (T, n)
    n_classes = forest.leaf_value.shape[2]
    per_class = [
        jnp.mean(jnp.take_along_axis(forest.leaf_value[:, :, c], idx, axis=1), axis=0)
        for c in range(n_classes)
    ]
    return jnp.stack(per_class, axis=1)


@partial(jax.jit, static_argnames=("max_depth",))
def forest_predict_reg(x: jax.Array, forest: Forest, max_depth: int) -> jax.Array:
    """(n,) mean of per-tree leaf means."""
    idx = forest_apply(x, forest, max_depth)
    lv = jnp.take_along_axis(forest.leaf_value[:, :, 0], idx, axis=1)  # (T, n)
    return jnp.mean(lv, axis=0)


def sample_weights(
    key: jax.Array, n_trees: int, n_rows: int, subsampling_rate: float, bootstrap: bool
) -> jax.Array:
    """Per-tree row weights: Poisson(rate) with replacement (the standard
    distributed approximation of bootstrap resampling), Bernoulli(rate)
    without.

    Poisson draws clamp at 256 — the bf16-exactness bound of the one-pass
    histogram (ops.trees.grow_forest precision note). A clamp at 256 is
    semantically invisible (P[Poisson(rate <= 1) > 256] ~ 1e-600: no draw
    ever reaches it) but makes the unweighted classification histogram's
    exactness a STATIC fact — one-hot stats x integer weights <= 256 are
    exact bf16 products — so the fit no longer pays a device readback to
    verify it (each readback is a full round trip under the relay
    tunnel; VERDICT r4 #2)."""
    if bootstrap:
        w = jax.random.poisson(key, subsampling_rate, (n_trees, n_rows))
        return jnp.minimum(w, 256).astype(jnp.float32)
    return jax.random.bernoulli(key, subsampling_rate, (n_trees, n_rows)).astype(
        jnp.float32
    )


def feature_importances(forest: Forest, n_features: int) -> np.ndarray:
    """Impurity-based importances, Spark-style: per tree, each split
    contributes gain * node_weight to its feature; per-tree vectors are
    normalized, averaged over trees, then renormalized to sum to 1."""
    feat = np.asarray(forest.feature)  # (T, N)
    gain = np.asarray(forest.node_gain)
    w = np.asarray(forest.node_weight)
    T = feat.shape[0]
    per_tree = np.zeros((T, n_features))
    contrib = gain * w
    for t in range(T):
        split = feat[t] >= 0
        np.add.at(per_tree[t], feat[t][split], contrib[t][split])
    sums = per_tree.sum(axis=1, keepdims=True)
    per_tree = np.divide(per_tree, sums, out=np.zeros_like(per_tree), where=sums > 0)
    avg = per_tree.mean(axis=0)
    s = avg.sum()
    return avg / s if s > 0 else avg
