"""Double-float ("double-double") extended-precision GEMM on fp32 hardware.

The reference's entire JNI surface is ``double[]`` (JniRAPIDSML.java:64-69)
and its test oracle assumes fp64 covariance accumulation; TPU MXUs have no
fp64 path (SURVEY.md §7 hard part #1). This module emulates extended
precision with unevaluated f32 pairs (value ≈ hi + lo), the technique used
for TPU linear algebra at scale (cf. "Large Scale Distributed Linear Algebra
With Tensor Processing Units", arXiv:2112.09017 — PAPERS.md):

  - operands split hi/lo (Dekker): each f64 input becomes two f32s;
  - the product X·Y expands to Xhi·Yhi + Xhi·Ylo + Xlo·Yhi (the lo·lo term
    is below the result's precision), each term an MXU matmul at
    precision=HIGHEST;
  - the contraction dimension is processed in chunks via lax.scan, chunk
    partials added into a running (hi, lo) accumulator with the exact
    two_sum of Knuth — so the long-K summation error does NOT grow with K
    the way a single f32 accumulator's would.

Accuracy contract (measured, tests/test_doubledouble.py): relative error
stays at the f32 epsilon floor (~2e-8) FLAT in the contraction length —
the intra-chunk matmul rounding is the floor; the compensated accumulator
stops the sqrt(K)/K growth a plain f32 accumulation suffers (≥100x better
at K=200k on positive sums, e.g. Gram diagonals). That meets the
reference's 1e-5-absolute oracle bar with orders of margin. It is NOT
bit-exact IEEE fp64; an error-free Ozaki-scheme splitting would be the
next step if true fp64 semantics were ever required.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def split_f64(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side split of an fp64 array into (hi, lo) f32 pair arrays."""
    x = np.asarray(x, dtype=np.float64)
    hi = x.astype(np.float32)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def _two_sum(a, b):
    """Knuth's exact two_sum: a + b = s + err, err captured exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _dd_add(hi, lo, x):
    """Add f32 array x into a (hi, lo) compensated accumulator."""
    s, e = _two_sum(hi, x)
    lo = lo + e
    return s, lo


@partial(jax.jit, static_argnames=("chunk",))
def matmul_dd(
    a_hi: jax.Array,
    a_lo: jax.Array,
    b_hi: jax.Array,
    b_lo: jax.Array,
    chunk: int = 512,
):
    """Extended-precision A·B for split operands; returns (hi, lo) f32 pair.

    A: (m, k), B: (k, n). The k dimension is scanned in ``chunk``-sized
    slices; each slice contributes three HIGHEST-precision MXU matmuls whose
    sum enters the compensated accumulator.
    """
    m, k = a_hi.shape
    n = b_hi.shape[1]
    nb = -(-k // chunk)
    pad = nb * chunk - k
    if pad:
        a_hi = jnp.pad(a_hi, ((0, 0), (0, pad)))
        a_lo = jnp.pad(a_lo, ((0, 0), (0, pad)))
        b_hi = jnp.pad(b_hi, ((0, pad), (0, 0)))
        b_lo = jnp.pad(b_lo, ((0, pad), (0, 0)))
    a_hi_c = a_hi.reshape(m, nb, chunk).transpose(1, 0, 2)
    a_lo_c = a_lo.reshape(m, nb, chunk).transpose(1, 0, 2)
    b_hi_c = b_hi.reshape(nb, chunk, n)
    b_lo_c = b_lo.reshape(nb, chunk, n)
    prec = jax.lax.Precision.HIGHEST

    def body(acc, operands):
        ah, al, bh, bl = operands
        hi, lo = acc
        main = jnp.matmul(ah, bh, precision=prec)
        cross = jnp.matmul(ah, bl, precision=prec) + jnp.matmul(al, bh, precision=prec)
        hi, lo = _dd_add(hi, lo, main)
        lo = lo + cross  # cross terms are already ~eps * main; plain add suffices
        return (hi, lo), None

    acc0 = (jnp.zeros((m, n), jnp.float32), jnp.zeros((m, n), jnp.float32))
    (hi, lo), _ = jax.lax.scan(body, acc0, (a_hi_c, a_lo_c, b_hi_c, b_lo_c))
    return hi, lo


def dd_to_f64(hi: jax.Array, lo: jax.Array) -> np.ndarray:
    """Recombine a (hi, lo) pair into a host fp64 array."""
    return np.asarray(hi, dtype=np.float64) + np.asarray(lo, dtype=np.float64)


def centered_gram_dd(x: np.ndarray, mean: np.ndarray, chunk: int = 2048) -> np.ndarray:
    """Extended-precision (x − mean)ᵀ(x − mean) from fp64 host input.

    The centering happens in fp64 on the host (exact to input precision),
    the Gram matmul in double-float on the accelerator, scanning the ROW
    dimension (the contraction axis of BᵀB) in chunks. Returns fp64.
    """
    b = np.asarray(x, dtype=np.float64) - np.asarray(mean, dtype=np.float64)
    b_hi, b_lo = split_f64(b)
    bt_hi, bt_lo = b_hi.T, b_lo.T
    hi, lo = matmul_dd(
        jnp.asarray(np.ascontiguousarray(bt_hi)),
        jnp.asarray(np.ascontiguousarray(bt_lo)),
        jnp.asarray(b_hi),
        jnp.asarray(b_lo),
        chunk=chunk,
    )
    return dd_to_f64(hi, lo)


def covariance_dd(x: np.ndarray, chunk: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """fp64-emulated sample covariance: returns (mean, cov) as fp64 arrays.

    The fp64-on-TPU answer for callers that need the reference's ``double[]``
    numerics on fp32 hardware — PCA and RowMatrix route here when
    ``precision="dd"`` is requested or auto-selected for float64 input.
    """
    x = np.asarray(x, dtype=np.float64)
    if x.shape[0] < 2:
        raise ValueError("need at least 2 rows to compute a covariance")
    mean = x.mean(axis=0)
    gram = centered_gram_dd(x, mean, chunk=chunk)
    return mean, gram / (x.shape[0] - 1)


def covariance_dd_blocks(
    partitions, center: bool = True, chunk: int = 2048
) -> Tuple[np.ndarray, np.ndarray, int]:
    """ONE-pass streaming dd covariance over host blocks (list, tuple, or
    generator — each block is visited exactly once, so device and host
    memory stay bounded by one block).

    The exact column mean is not known until the stream ends, so blocks are
    centered on the FIRST block's column means (exact host-fp64 subtract —
    the shifted-accumulation scheme of the native Kahan runtime,
    native/src/tpuml_host.cpp), the shifted Gram accumulates through
    extended-precision GEMMs, and the closed-form correction
    ``Σx̃ᵀx̃ − n·δδᵀ`` (δ = mean of shifted values) recovers the true
    centered Gram. Shift error never touches the large raw magnitudes, so
    the dd error floor holds even for means ≫ stddevs. Returns
    ``(mean, cov, n)`` with cov normalized by (n − 1) — the RowMatrix
    contract (RapidsRowMatrix.scala:168-201, per-partition compute +
    cross-partition reduce).
    """
    from spark_rapids_ml_tpu.ops.covariance import (
        finalize_shifted_gram,
        shifted_block_scan,
    )

    def gram_fn(bs):
        return centered_gram_dd(bs, np.zeros(bs.shape[1]), chunk=chunk)

    return finalize_shifted_gram(*shifted_block_scan(partitions, center, gram_fn), center)


def normal_eq_stats_dd(block_pairs, chunk: int = 2048):
    """Extended-precision normal-equation sufficient statistics over an
    iterable of (X, y) host blocks, in ONE streaming pass.

    Returns ``(xtx, xty, x_sum, y_sum, yty, count)`` as fp64 arrays — the
    same raw-moment tuple contract as ``ops.linear.normal_eq_stats``, at the
    reference's ``double[]`` numerics bar (JniRAPIDSML.java:64-69).

    The accelerator GEMMs run on SHIFTED values (x − shift, with shift = the
    first block's column means, subtracted in exact host fp64): a dd GEMM of
    raw ill-conditioned data (means ≫ stddevs) would put its f32-eps
    *relative* error on the huge raw moments, which the solver's centering
    subtraction then amplifies catastrophically. Shifting keeps the GEMM
    operands O(std), and the raw moments are reconstructed from the shifted
    ones by closed-form fp64 outer-product corrections (the shifted-
    accumulation scheme of the native Kahan ``spr`` runtime,
    native/src/tpuml_host.cpp).
    """
    shift = None  # (d,) first-block column means
    y_shift = 0.0
    g = v = s = None  # shifted: Σx̃ᵀx̃ (dd), Σx̃ᵀỹ (dd), Σx̃ (fp64)
    sy = syy = 0.0  # Σỹ, Σỹ²
    count = 0
    for xb, yb in block_pairs:
        x = np.asarray(xb, dtype=np.float64)
        y = np.asarray(yb, dtype=np.float64).ravel()
        if x.shape[0] != y.shape[0]:
            raise ValueError(
                f"block rows mismatch: X has {x.shape[0]}, y has {y.shape[0]}"
            )
        if x.shape[0] == 0:
            continue
        if shift is None:
            shift = x.mean(axis=0)
            y_shift = float(y.mean())
        # One dd Gram of [x̃ | ỹ] per block: its top-left d x d is Σx̃ᵀx̃,
        # last column Σx̃ᵀỹ, corner Σỹ² — one device dispatch instead of
        # separate XᵀX / Xᵀy scans (and one jit specialization per shape).
        z = np.concatenate([x - shift, (y - y_shift)[:, None]], axis=1)
        z_hi, z_lo = split_f64(z)
        zt_hi = np.ascontiguousarray(z_hi.T)
        zt_lo = np.ascontiguousarray(z_lo.T)
        g_hi, g_lo = matmul_dd(
            jnp.asarray(zt_hi), jnp.asarray(zt_lo),
            jnp.asarray(z_hi), jnp.asarray(z_lo), chunk=chunk,
        )
        g_blk = dd_to_f64(g_hi, g_lo)
        d = z.shape[1] - 1
        g = g_blk[:d, :d] if g is None else g + g_blk[:d, :d]
        v = g_blk[:d, d] if v is None else v + g_blk[:d, d]
        s_blk = z[:, :d].sum(axis=0)
        s = s_blk if s is None else s + s_blk
        sy += float(z[:, d].sum())
        # Σỹ² stays exact host fp64 (O(n) — no reason to take the dd floor).
        syy += float(np.dot(z[:, d], z[:, d]))
        count += x.shape[0]
    if count == 0:
        raise ValueError("no rows to accumulate")
    n = float(count)
    # Undo the shift in closed form (exact fp64 outer products; the shift
    # terms cancel identically when the solver re-centers, so no f32-level
    # error ever lands on the large raw magnitudes).
    xtx = (
        g
        + np.outer(s, shift)
        + np.outer(shift, s)
        + n * np.outer(shift, shift)
    )
    xty = v + y_shift * s + sy * shift + n * y_shift * shift
    x_sum = s + n * shift
    y_sum = sy + n * y_shift
    yty = syy + 2.0 * y_shift * sy + n * y_shift * y_shift
    return xtx, xty, x_sum, np.float64(y_sum), np.float64(yty), np.float64(count)
