"""Linear model kernels — normal-equation sufficient statistics on the MXU.

Beyond-PCA capability (BASELINE.md config 4: "LinearRegression / Ridge on
HIGGS 11M x 28 — normal-equation GEMM path"). The sufficient statistics
(X^T X, X^T y, column sums) are one fused jitted computation — the same
masked/shardable shape as the covariance kernel, so the distributed story is
identical: row-shard x/y over the mesh data axis and XLA inserts the psum.

Solve semantics follow Spark ML's "normal" solver (WeightedLeastSquares):
    minimize 1/(2n) ||y - X b - b0||^2 + regParam * penalty(b)
with L2 penalty applied to coefficients of STANDARDIZED features when
``standardization`` is on, i.e. in original space
    (Xc^T Xc + n * regParam * diag(sigma^2)) b = Xc^T yc
(sigma = per-feature stddev; identity instead of diag(sigma^2) when
standardization is off), intercept b0 = mean(y) - mean(x)^T b.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import soft_threshold
from spark_rapids_ml_tpu.ops.precision import make_dot


@partial(jax.jit, static_argnames=("precision",))
def normal_eq_stats(
    x: jax.Array, y: jax.Array, mask: jax.Array | None, precision: str = "highest"
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Masked sufficient statistics in one pass.

    Returns (xtx, xty, x_sum, y_sum, yty, count): raw (uncentered) moments;
    centering happens in the solver where it is O(d^2), not O(n d).

    ``mask=None`` means "all rows real, weight 1" and skips the masking
    multiplies entirely — at small d this config is bytes-bound and the
    x*mask pass would nearly double the HBM traffic for nothing.
    """
    dot = make_dot(precision)
    if mask is None:
        xtx = dot(x.T, x)
        xty = dot(x.T, y)
        n = jnp.asarray(x.shape[0], x.dtype)
        return (xtx, xty, jnp.sum(x, axis=0), jnp.sum(y), jnp.sum(y * y), n)
    xm = x * mask[:, None]
    ym = y * mask
    xtx = dot(xm.T, x)
    xty = dot(xm.T, y)
    return (
        xtx,
        xty,
        jnp.sum(xm, axis=0),
        jnp.sum(ym),
        jnp.sum(ym * y),
        jnp.sum(mask),
    )


def _centered_moments(xtx, xty, x_sum, y_sum, count, fit_intercept, standardization):
    """Shared pre-solve reduction: centered Gram/cross moments, means, and
    the per-feature variance used as the standardization penalty weight.

    sigma^2 is the TRUE feature variance (centered second moment) in both
    intercept modes — Spark standardizes by the feature stddev regardless
    of fitIntercept. Returns (a, b, x_mean, y_mean, var_weights).
    """
    n = count
    x_mean = x_sum / n
    y_mean = y_sum / n
    if fit_intercept:
        # centered moments: Xc^T Xc = X^T X - n * mean mean^T
        a = xtx - n * jnp.outer(x_mean, x_mean)
        b = xty - n * x_mean * y_mean
    else:
        a = xtx
        b = xty
    if standardization:
        var = jnp.maximum(
            (jnp.diag(xtx) - n * x_mean * x_mean) / jnp.maximum(n - 1, 1), 0.0
        )
    else:
        var = jnp.ones(a.shape[0], dtype=a.dtype)
    return a, b, x_mean, y_mean, var


@partial(jax.jit, static_argnames=("fit_intercept", "standardization"))
def solve_normal(
    xtx: jax.Array,
    xty: jax.Array,
    x_sum: jax.Array,
    y_sum: jax.Array,
    count: jax.Array,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    standardization: bool = True,
):
    """Solve the (regularized) normal equations from raw moments.

    Returns (coefficients (d,), intercept scalar). Cholesky with a
    singularity fallback to eigh-based pseudo-solve (minimum-norm), which
    handles rank-deficient designs the way LAPACK-backed Spark does via
    quasi-Newton fallback.
    """
    n = count
    a, b, x_mean, y_mean, penalty = _centered_moments(
        xtx, xty, x_sum, y_sum, count, fit_intercept, standardization
    )
    d = a.shape[0]
    a_reg = a + (n * reg_param) * jnp.diag(penalty)

    chol, low = jax.scipy.linalg.cho_factor(a_reg, lower=True)
    coef_chol = jax.scipy.linalg.cho_solve((chol, low), b)
    ok = jnp.all(jnp.isfinite(coef_chol))

    # minimum-norm pseudo-solve fallback for singular/indefinite systems
    w, v = jnp.linalg.eigh(a_reg)
    tol = jnp.max(jnp.abs(w)) * d * jnp.finfo(a.dtype).eps
    w_inv = jnp.where(w > tol, 1.0 / w, 0.0)
    coef_pinv = v @ (w_inv * (v.T @ b))

    coef = jnp.where(ok, coef_chol, coef_pinv)
    intercept = jnp.where(fit_intercept, y_mean - jnp.dot(x_mean, coef), 0.0)
    return coef, intercept


@partial(jax.jit, static_argnames=("precision",))
def predict_linear(x: jax.Array, coef: jax.Array, intercept, precision: str = "highest"):
    return make_dot(precision)(x, coef) + intercept


@jax.jit
def regression_metrics(y: jax.Array, pred: jax.Array, mask: jax.Array):
    """(mse, rmse, mae, r2) over unmasked rows."""
    n = jnp.sum(mask)
    resid = (y - pred) * mask
    sse = jnp.sum(resid * resid)
    mse = sse / n
    mae = jnp.sum(jnp.abs(resid)) / n
    y_mean = jnp.sum(y * mask) / n
    sst = jnp.sum(((y - y_mean) * mask) ** 2)
    r2 = 1.0 - sse / jnp.where(sst > 0, sst, 1.0)
    return mse, jnp.sqrt(mse), mae, r2


@partial(jax.jit, static_argnames=("fit_intercept", "standardization", "max_iter"))
def solve_elastic_net(
    xtx: jax.Array,
    xty: jax.Array,
    x_sum: jax.Array,
    y_sum: jax.Array,
    count: jax.Array,
    reg_param: float,
    elastic_net_param: float,
    fit_intercept: bool = True,
    standardization: bool = True,
    max_iter: int = 2000,
    tol: float = 1e-7,
    init_coef=None,
):
    """Elastic-net least squares from the SAME sufficient statistics.

    minimize 1/(2n)||y - Xb - b0||^2
             + regParam * (alpha * sum_j w1_j |b_j|
                           + (1-alpha)/2 * sum_j w2_j b_j^2)
    with w1 = sigma, w2 = sigma^2 under standardization (the original-space
    form of penalizing standardized coefficients, matching the L2 path),
    w = 1 otherwise. Solved by FISTA on the quadratic moment form — the
    gradient is (A b - B)/n with A = Xc^T Xc, so iterations are O(d^2)
    vector-matrix work independent of n: the data was consumed by ONE GEMM
    pass (``normal_eq_stats``), the accelerated proximal loop never touches
    it again. Returns (coefficients, intercept, n_iter).
    """
    n = count
    a, b, x_mean, y_mean, w2 = _centered_moments(
        xtx, xty, x_sum, y_sum, count, fit_intercept, standardization
    )
    d = a.shape[0]
    w1 = jnp.sqrt(w2) if standardization else jnp.ones(d, dtype=a.dtype)

    alpha = elastic_net_param
    a_quad = a / n + reg_param * (1.0 - alpha) * jnp.diag(w2)
    b_lin = b / n
    l1 = reg_param * alpha * w1  # per-coordinate soft-threshold level

    # Lipschitz constant of the quadratic part: its largest eigenvalue.
    lip = jnp.maximum(jnp.max(jnp.linalg.eigvalsh(a_quad)), 1e-12)

    def cond(carry):
        _, _, _, it, delta = carry
        return jnp.logical_and(it < max_iter, delta > tol)

    def body(carry):
        c, z, t, it, _ = carry
        grad = a_quad @ z - b_lin
        c_new = soft_threshold(z - grad / lip, l1 / lip)
        t_new = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
        z_new = c_new + ((t - 1.0) / t_new) * (c_new - c)
        delta = jnp.max(jnp.abs(c_new - c))
        return c_new, z_new, t_new, it + 1, delta

    # Warm start (partial_fit / regularization-path sweeps): FISTA from
    # a previous optimum in the ORIGINAL coefficient space — the carry's
    # own space, so no mapping is needed. Momentum restarts from the
    # seed (z = c, t = 1): plain FISTA initialization, just not at zero.
    c0 = (
        jnp.zeros(d, dtype=a.dtype)
        if init_coef is None
        else jnp.asarray(init_coef, dtype=a.dtype)
    )
    init = (c0, c0, jnp.asarray(1.0, a.dtype), 0, jnp.asarray(jnp.inf, a.dtype))
    coef, _, _, n_iter, _ = jax.lax.while_loop(cond, body, init)
    intercept = jnp.where(fit_intercept, y_mean - jnp.dot(x_mean, coef), 0.0)
    return coef, intercept, n_iter


@partial(jax.jit, static_argnames=("fit_intercept", "standardization"))
def _enet_prep(
    xtx, xty, x_sum, y_sum, count, reg_param, elastic_net_param,
    fit_intercept: bool, standardization: bool,
):
    """:func:`solve_elastic_net`'s pre-loop reduction (quadratic form,
    soft-threshold levels, Lipschitz constant, means) as one small
    program, shared by every segment of a resumable solve."""
    n = count
    a, b, x_mean, y_mean, w2 = _centered_moments(
        xtx, xty, x_sum, y_sum, count, fit_intercept, standardization
    )
    d = a.shape[0]
    w1 = jnp.sqrt(w2) if standardization else jnp.ones(d, dtype=a.dtype)
    alpha = elastic_net_param
    a_quad = a / n + reg_param * (1.0 - alpha) * jnp.diag(w2)
    b_lin = b / n
    l1 = reg_param * alpha * w1
    lip = jnp.maximum(jnp.max(jnp.linalg.eigvalsh(a_quad)), 1e-12)
    return a_quad, b_lin, l1, lip, x_mean, y_mean


@partial(jax.jit, static_argnames=("max_iter", "every"))
def _enet_segment(
    a_quad, b_lin, l1, lip, tol, coef, z, t, it, delta,
    max_iter: int, every: int,
):
    """Up to ``every`` FISTA iterations from an explicit carry — exactly
    :func:`solve_elastic_net`'s loop body and stopping rule plus a
    segment budget, the (coef, momentum, t, iteration, delta) state a
    pytree between segments."""

    def cond(carry):
        _, _, _, it, delta, seg = carry
        return jnp.logical_and(
            jnp.logical_and(it < max_iter, delta > tol), seg < every
        )

    def body(carry):
        c, z, t, it, _, seg = carry
        grad = a_quad @ z - b_lin
        c_new = soft_threshold(z - grad / lip, l1 / lip)
        t_new = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
        z_new = c_new + ((t - 1.0) / t_new) * (c_new - c)
        delta = jnp.max(jnp.abs(c_new - c))
        return c_new, z_new, t_new, it + 1, delta, seg + 1

    coef, z, t, it, delta, _ = jax.lax.while_loop(
        cond, body, (coef, z, t, it, delta, 0)
    )
    return coef, z, t, it, delta


def solve_elastic_net_resumable(
    xtx, xty, x_sum, y_sum, count,
    reg_param: float,
    elastic_net_param: float,
    checkpointer,
    fit_intercept: bool = True,
    standardization: bool = True,
    max_iter: int = 2000,
    tol: float = 1e-7,
    init_coef=None,
    mesh=None,
):
    """Preemption-tolerant :func:`solve_elastic_net`: host outer loop
    over jitted FISTA segments with async checkpoint snapshots between
    them. Same returns (coefficients, intercept, n_iter), bit-identical."""
    from spark_rapids_ml_tpu.robustness.checkpoint import (
        replicate_state_onto_mesh,
        segment_boundary,
    )
    import time

    from spark_rapids_ml_tpu.observability.costs import ledgered_call
    from spark_rapids_ml_tpu.observability.metrics import observe_segment_seconds
    from spark_rapids_ml_tpu.robustness.faults import fault_point
    from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange, bump_counter

    a_quad, b_lin, l1, lip, x_mean, y_mean = _enet_prep(
        xtx, xty, x_sum, y_sum, count, reg_param, elastic_net_param,
        fit_intercept=fit_intercept, standardization=standardization,
    )
    d = a_quad.shape[0]
    dt = a_quad.dtype
    # Same warm-start contract as solve_elastic_net: original-space seed,
    # momentum restarted at the seed.
    c0 = (
        jnp.zeros(d, dtype=dt)
        if init_coef is None
        else jnp.asarray(init_coef, dtype=dt)
    )
    carry = (
        c0, c0, jnp.asarray(1.0, dt), jnp.asarray(0), jnp.asarray(jnp.inf, dt)
    )
    restored = checkpointer.restore_latest(template=carry)
    if restored is not None:
        _, carry = restored
        if mesh is not None:
            carry = replicate_state_onto_mesh(carry, mesh)

    while True:
        it, delta = int(carry[3]), float(carry[4])
        if not (it < max_iter and delta > tol):
            break
        seg_t0 = time.perf_counter()
        with TraceRange("segment linear.enet", TraceColor.PURPLE):
            fault_point("solver.segment")
            carry = ledgered_call(
                _enet_segment, (a_quad, b_lin, l1, lip, tol, *carry),
                static=dict(max_iter=max_iter, every=checkpointer.every),
                name="linear.enet.segment",
            )
            bump_counter("checkpoint.segments")
            bump_counter("checkpoint.solver_iters", int(carry[3]) - it)
        observe_segment_seconds("linear.enet", time.perf_counter() - seg_t0)
        checkpointer.save_async(int(carry[3]), carry)
        segment_boundary(checkpointer)

    coef, _, _, n_iter, _ = carry
    intercept = jnp.where(fit_intercept, y_mean - jnp.dot(x_mean, coef), 0.0)
    checkpointer.finalize_success()
    return coef, intercept, n_iter


def solve_normal_host(
    xtx,
    xty,
    x_sum,
    y_sum,
    count,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    standardization: bool = True,
):
    """Host fp64 twin of :func:`solve_normal` — same math, NumPy/LAPACK.

    The dd precision path accumulates its sufficient statistics as exact
    fp64 (ops.doubledouble.normal_eq_stats_dd); solving them through the
    jitted fp32 path would throw that precision away on a no-x64 platform,
    so the O(d^3) solve runs on the host in fp64 (the reference's
    driver-side breeze/LAPACK position, RapidsRowMatrix.scala:110-123).
    """
    import numpy as np

    xtx = np.asarray(xtx, dtype=np.float64)
    xty = np.asarray(xty, dtype=np.float64)
    x_sum = np.asarray(x_sum, dtype=np.float64)
    n = float(count)
    x_mean = x_sum / n
    y_mean = float(y_sum) / n
    if fit_intercept:
        a = xtx - n * np.outer(x_mean, x_mean)
        b = xty - n * x_mean * y_mean
    else:
        a = xtx
        b = xty
    if standardization:
        var = np.maximum(
            (np.diag(xtx) - n * x_mean * x_mean) / max(n - 1.0, 1.0), 0.0
        )
    else:
        var = np.ones(a.shape[0], dtype=np.float64)
    a_reg = a + (n * reg_param) * np.diag(var)
    try:
        coef = np.linalg.solve(a_reg, b)
        if not np.all(np.isfinite(coef)):
            raise np.linalg.LinAlgError
    except np.linalg.LinAlgError:
        w, v = np.linalg.eigh(a_reg)
        tol = np.max(np.abs(w)) * a.shape[0] * np.finfo(np.float64).eps
        w_inv = np.where(w > tol, 1.0 / np.where(w > tol, w, 1.0), 0.0)
        coef = v @ (w_inv * (v.T @ b))
    intercept = (y_mean - float(np.dot(x_mean, coef))) if fit_intercept else 0.0
    return coef, intercept


def normal_eq_stats_streaming(block_pairs, dtype=None, precision: str = "highest"):
    """Accumulate the sufficient statistics over an ITERABLE of (X, y)
    blocks — the streaming form of :func:`normal_eq_stats`.

    Every downstream solver (normal equations, ridge, elastic-net FISTA)
    consumes only these O(d^2) moments, so a dataset of any length fits in
    one block of device memory at a time. Blocks may come from a generator
    (e.g. ``native.NpyBlockReader.iter_blocks``) and are consumed lazily —
    nothing is concatenated on the host.

    Returns the same (xtx, xty, x_sum, y_sum, yty, count) tuple.
    """
    import numpy as np

    from spark_rapids_ml_tpu.robustness.faults import fault_point

    def _upload(pair):
        xb, yb = pair
        if getattr(xb, "shape", (1,))[0] == 0:
            # Empty partitions densify to (0, 0) — no rows, no width info.
            return None
        return (
            jnp.asarray(np.ascontiguousarray(xb), dtype=dtype),
            jnp.asarray(np.ascontiguousarray(yb), dtype=dtype),
        )

    from spark_rapids_ml_tpu.core.serving import prefetch_blocks

    acc = None
    d = None
    # Double-buffered: pair k+1 densifies/uploads while pair k's moment
    # program runs; accumulation order is unchanged (bit-identical).
    for pair in prefetch_blocks(block_pairs, _upload):
        if pair is None:
            continue
        xj, yj = pair
        fault_point("solver.segment")
        if d is None:
            d = xj.shape[1]
        elif xj.shape[1] != d:
            raise ValueError(
                f"inconsistent feature dims across blocks: {xj.shape[1]} vs {d}"
            )
        if xj.shape[0] != yj.shape[0]:
            raise ValueError(
                f"block rows mismatch: X has {xj.shape[0]}, y has {yj.shape[0]}"
            )
        mask = jnp.ones(xj.shape[0], dtype=xj.dtype)
        stats = normal_eq_stats(xj, yj, mask, precision=precision)
        acc = stats if acc is None else tuple(a + s for a, s in zip(acc, stats))
    if acc is None:
        raise ValueError("no blocks to accumulate")
    return acc
