"""Pallas TPU kernel: bucketed tail scatter-add for the UMAP layout SGD.

The synchronous UMAP epoch (ops.umap._make_epoch_fn) applies every edge's
attractive gradient twice: once to the head (a DENSE (n, k, dim) sum — free)
and once to the tail (``zeros.at[dst].add(g)`` — a true scatter over random
indices). XLA lowers that scatter element-serialized, and it measured ~70%
of the whole SGD wall at config 13 (VERDICT r5 #1: 10.9 ms/epoch of a
15.6 ms epoch).

The edge list is STATIC per fit, so the randomness can be paid ONCE on the
host instead of every epoch on the device: sort the E = n*k edges by tail
index at graph-build time (:func:`build_tail_plan`), and each epoch becomes

    per-edge gradients --[one row gather by the static perm]--> tail-sorted
    --[this kernel]--> dense per-tile accumulation in VMEM.

The kernel walks output tiles of ``rows_per_tile`` embedding rows; because
edges arrive tail-sorted, each tile's contributions live in a CONTIGUOUS
slice of the edge stream, covered by a per-tile run of ``edges_per_block``
blocks (host-computed base/length, scalar-prefetched so the index maps are
static). Each block contributes via a one-hot contraction

    out(sub, R) += v(sub, EB) . onehot(R, EB)    # contract over EB

so the accumulator is written once per tile — no per-element scatter ever
reaches HBM. Out-of-tile edges in boundary blocks (and the sentinel-padded
tail of the stream) fall outside the tile's one-hot range and contribute
exactly zero — masking is free.

Determinism: the accumulation order WITHIN a tile is the sorted-edge order,
which differs from XLA's scatter order — results agree with the XLA path to
float tolerance, not bitwise (PARITY.md, ``TPUML_UMAP_SCATTER``). Segmented
and monolithic fits share one plan, so checkpoint bit-identity holds.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


class TailCfg(NamedTuple):
    """Static (hashable) geometry of a tail plan — a jit static argument."""

    n: int                # true embedding rows
    dim: int              # embedding width (<= sub)
    sub: int              # sublane-padded width (multiple of 8)
    e: int                # true edge count n * k
    e_pad: int            # edge stream padded to edges_per_block multiples
    n_pad: int            # rows padded to rows_per_tile multiples
    rows_per_tile: int    # output tile width R (multiple of 128)
    edges_per_block: int  # edge block length EB (multiple of 128)
    max_nblocks: int      # widest per-tile block run (the static grid dim)


class TailPlan(NamedTuple):
    """Device-side arrays of the per-fit edge sort (a traced pytree)."""

    perm: jax.Array       # (e,) int32 edge permutation: tail-sorted order
    tails: jax.Array      # (1, e_pad) int32 sorted tails, sentinel-padded
    base: jax.Array       # (n_tiles,) int32 first edge BLOCK of each tile
    nblk: jax.Array       # (n_tiles,) int32 block-run length of each tile


def build_tail_plan(
    indices: np.ndarray,
    n: int,
    dim: int,
    rows_per_tile: int = 256,
    edges_per_block: int = 1024,
) -> Tuple[TailPlan, TailCfg]:
    """Host-side edge sort + tile coverage for one fitted graph.

    ``indices``: the (n, k) kNN tail ids (host copy — the graph is static
    per fit, so this runs once, outside every epoch). The returned plan is
    valid for any per-edge value stream laid out head-major (n * k rows),
    which is exactly ``g_att.reshape(-1, dim)``'s order.
    """
    tails = np.asarray(indices, dtype=np.int32).reshape(-1)
    e = tails.shape[0]
    perm = np.argsort(tails, kind="stable").astype(np.int32)
    tails_sorted = tails[perm]

    e_pad = e + (-e) % edges_per_block
    n_pad = n + (-n) % rows_per_tile
    n_tiles = n_pad // rows_per_tile
    total_blocks = e_pad // edges_per_block
    # Sentinel tails land past every tile's one-hot range: padded edges
    # contribute zero without any mask traffic.
    tails_full = np.full((e_pad,), n_pad, dtype=np.int32)
    tails_full[:e] = tails_sorted

    bounds = np.arange(n_tiles + 1, dtype=np.int64) * rows_per_tile
    cut = np.searchsorted(tails_sorted, bounds, side="left")
    start, stop = cut[:-1], cut[1:]
    base = np.minimum(start // edges_per_block, total_blocks - 1)
    last = np.ceil(stop / edges_per_block).astype(np.int64)
    nblk = np.maximum(last - base, 0)
    nblk[stop <= start] = 0
    max_nblocks = max(int(nblk.max()), 1) if n_tiles else 1

    cfg = TailCfg(
        n=n, dim=dim, sub=dim + (-dim) % 8, e=e, e_pad=e_pad, n_pad=n_pad,
        rows_per_tile=rows_per_tile, edges_per_block=edges_per_block,
        max_nblocks=max_nblocks,
    )
    plan = TailPlan(
        perm=jnp.asarray(perm),
        tails=jnp.asarray(tails_full[None, :]),
        base=jnp.asarray(base.astype(np.int32)),
        nblk=jnp.asarray(nblk.astype(np.int32)),
    )
    return plan, cfg


def plan_feasible(n: int, k: int, dim: int) -> bool:
    """True when the bucketed kernel is worth dispatching: the one-hot
    block scratch plus in/out tiles sit well inside VMEM at the default
    geometry, and the embedding width fits one sublane tile."""
    if dim > 128:
        return False  # (sub, EB) v-tiles would crowd VMEM; XLA path instead
    # one-hot (R, EB) + v (sub, EB) + out (sub, R) + tails, f32/int32.
    sub = dim + (-dim) % 8
    elems = 256 * 1024 + sub * 1024 + sub * 256 + 1024
    return elems * 4 < (4 << 20) and n * k > 0


def _tail_kernel(base_ref, nblk_ref, t_ref, v_ref, out_ref, *, rows_per_tile):
    r = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(j < nblk_ref[r])
    def _():
        # onehot[c, e] = 1 iff edge e's tail is row r*R + c. Built in the
        # (R, EB) orientation so no (1, EB) -> (EB, 1) relayout is needed:
        # the row iota runs along sublanes, the tails broadcast along them.
        local = t_ref[:] - r * rows_per_tile  # (1, EB)
        oh = (
            jax.lax.broadcasted_iota(
                jnp.int32, (rows_per_tile, local.shape[1]), 0
            )
            == local
        ).astype(jnp.float32)  # (R, EB)
        out_ref[:] += jax.lax.dot_general(
            v_ref[:], oh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (sub, R)


@partial(jax.jit, static_argnames=("cfg", "interpret"))
def tail_accumulate(
    g: jax.Array, plan: TailPlan, cfg: TailCfg, interpret: bool = False
) -> jax.Array:
    """Sum per-edge rows into per-tail rows: the scatter-add replacement.

    ``g``: (e, dim) per-edge contributions in head-major edge order (the
    natural ``reshape(-1, dim)`` of the epoch's (n, k, dim) gradients).
    Returns (n, dim) with row t = sum of g over edges whose tail is t —
    same contraction the XLA scatter computes, dense-accumulated per tile.
    """
    if g.shape != (cfg.e, cfg.dim):
        raise ValueError(f"edge values {g.shape} != plan ({cfg.e}, {cfg.dim})")
    v = jnp.take(g, plan.perm, axis=0)  # (e, dim) tail-sorted, one row gather
    vt = jnp.pad(v.T, ((0, cfg.sub - cfg.dim), (0, cfg.e_pad - cfg.e)))
    n_tiles = cfg.n_pad // cfg.rows_per_tile

    def edge_block(r, j, base, nblk):
        # Past-the-run steps re-point at the run's last block: Mosaic sees
        # an unchanged index and skips the copy; @pl.when skips the math.
        return (0, base[r] + jnp.minimum(j, jnp.maximum(nblk[r] - 1, 0)))

    out = pl.pallas_call(
        partial(_tail_kernel, rows_per_tile=cfg.rows_per_tile),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n_tiles, cfg.max_nblocks),
            in_specs=[
                pl.BlockSpec((1, cfg.edges_per_block), edge_block),
                pl.BlockSpec((cfg.sub, cfg.edges_per_block), edge_block),
            ],
            out_specs=pl.BlockSpec(
                (cfg.sub, cfg.rows_per_tile), lambda r, j, base, nblk: (0, r)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((cfg.sub, cfg.n_pad), jnp.float32),
        interpret=interpret,
    )(plan.base, plan.nblk, plan.tails, vt)
    return out[: cfg.dim, : cfg.n].T


__all__ = [
    "TailCfg",
    "TailPlan",
    "build_tail_plan",
    "plan_feasible",
    "tail_accumulate",
]
