"""Pallas TPU kernel: fused KMeans assignment + update statistics.

The XLA Lloyd step (ops.kmeans.lloyd_step) materializes two (n, k) HBM
temporaries per iteration — the distance matrix (consumed by argmin/min)
and the one-hot matrix (operand of the stats GEMM). At 20M x 16, k=100
that is ~32 GB of HBM write+read traffic per pass against a 1.3 GB data
read: the pass is temporary-bound, not data-bound (VERDICT r3 #2 — the
bytes-roofline gap). This kernel keeps both temporaries in VMEM: per row
block it computes scores, argmin, one-hot, and the (k, d) partial sums
without writing anything block-sized back to HBM. The only HBM traffic is
the streaming read of X — the true roofline.

Why round 3's attempt was ~20x SLOWER and this one is not: the r3 kernel
read X in its natural (n, d) layout, so at d=16 each VMEM tile used 16 of
128 lanes (and the HBM layout paid the same padding). Here X arrives
TRANSPOSED — (d, n): n runs along the lane dimension (dense tiles at any
d), d along sublanes (padded to 8, zeros contribute nothing). The two
dot_generals contract over d (scores) and over the block dimension
(stats) — both MXU ops; argmin/one-hot live on the VPU between them.

Padding rows (zero columns of x_t beyond n_true) all land in the SAME
deterministic cluster argmin(c2) with distance min(c2) and zero vector
sum — the caller subtracts that closed-form contribution instead of
streaming a mask (lloyd_fused below).

Supports the unweighted fit (the adapter's weighted path keeps the
masked XLA formulation).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_rapids_ml_tpu.ops.precision import pallas_precision

# Unused-slot score sentinel. Historically +inf; a FINITE bf16-exact
# power of two now, because the 3-pass compensated split is undefined on
# non-finite values (hi(inf) = inf, lo = inf - inf = NaN — and bf16
# saturates to inf at 3.4e38, earlier than many f32 intermediates). Any
# real squared-norm score is astronomically below 2^125 ≈ 4.3e37, so the
# argmin/min semantics are unchanged bit-for-bit.
_UNUSED_SCORE = 2.0 ** 125


def _split_hi_lo(a):
    """bf16 hi/lo split (in f32 containers): a == hi + lo with both parts
    bf16-representable, so DEFAULT-precision (1-pass) dots on the parts
    are exact products — the building block of the 3-pass f32-grade dot."""
    hi = a.astype(jnp.bfloat16).astype(jnp.float32)
    return hi, a - hi


def _dot_prec(a, b, dims, precision):
    """dot_general at the named precision. Mosaic has no HIGH mapping, so
    "high" is emulated as the classic 3-pass bf16 split
    (hi*hi + hi*lo + lo*hi — drops only the lo*lo term, ~f32 accuracy at
    half of HIGHEST's six passes)."""
    kw = dict(dimension_numbers=dims, preferred_element_type=jnp.float32)
    if precision == "high":
        a_hi, a_lo = _split_hi_lo(a)
        b_hi, b_lo = _split_hi_lo(b)
        default = jax.lax.Precision.DEFAULT
        return (
            jax.lax.dot_general(a_hi, b_hi, precision=default, **kw)
            + jax.lax.dot_general(a_hi, b_lo, precision=default, **kw)
            + jax.lax.dot_general(a_lo, b_hi, precision=default, **kw)
        )
    prec = (
        jax.lax.Precision.HIGHEST
        if precision == "highest"
        else jax.lax.Precision.DEFAULT
    )
    return jax.lax.dot_general(a, b, precision=prec, **kw)


def _assign_stats_kernel(xt_ref, ct_ref, c2_ref, sums_ref, counts_ref,
                         cost_ref, *, precision):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        # Dtype pinned explicitly: under x64, older interpret-mode state
        # discharge writes the weak 0.0 literal as f64 into the f32 ref.
        cost_ref[0, 0] = jnp.float32(0.0)

    xt = xt_ref[:]  # (d_pad, bn)
    # scores = c2 - 2 x.c  (the x2 term is argmin-invariant per row; the
    # true distance comes back via sum(x2) added to sum(min scores)).
    xc = _dot_prec(
        xt, ct_ref[:], (((0,), (0,)), ((), ())), precision
    )  # (bn, k_pad)
    scores = c2_ref[:] - 2.0 * xc
    m = jnp.min(scores, axis=1, keepdims=True)  # (bn, 1)
    labels = jnp.argmin(scores, axis=1)  # (bn,)
    oh = (
        jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        == labels[:, None]
    ).astype(jnp.float32)  # (bn, k_pad), exact 0/1
    # Stats GEMM: oh is EXACT in bf16 (0/1), so "high" needs only the x
    # split — oh.x_hi + oh.x_lo is exact-product f32-grade in 2 passes.
    if precision == "high":
        xt_hi, xt_lo = _split_hi_lo(xt)
        default = jax.lax.Precision.DEFAULT
        kw = dict(
            dimension_numbers=(((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        sums_ref[:] += jax.lax.dot_general(
            oh, xt_hi, precision=default, **kw
        ) + jax.lax.dot_general(oh, xt_lo, precision=default, **kw)
    else:
        sums_ref[:] += _dot_prec(
            oh, xt, (((0,), (1,)), ((), ())), precision
        )  # (k_pad, d_pad)
    counts_ref[:] += jnp.sum(oh, axis=0, keepdims=True)  # (1, k_pad)
    cost_ref[0, 0] += jnp.sum(xt * xt) + jnp.sum(m)


@partial(jax.jit, static_argnames=("block_n", "precision", "interpret"))
def assign_stats_fused(
    xt: jax.Array,
    centers: jax.Array,
    block_n: int = 4096,
    precision: str = "highest",
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused Lloyd statistics for TRANSPOSED input.

    ``xt``: (d_pad, n_pad) with d padded to 8 and n padded to ``block_n``
    multiples, both zero-filled (use :func:`pad_transposed`). ``centers``:
    (k, d_pad). Returns raw ``(sums (k, d_pad), counts (k,), cost,
    c2 (k,))`` INCLUDING the padding rows' contribution — callers subtract
    it in closed form (see :func:`lloyd_fused`). ``c2`` is the EXACT
    squared-norm row the kernel scored against (computed from the
    transposed ``ct`` buffer): the padding correction must take its argmin
    over THIS buffer, not a recomputation from ``centers`` — a different
    reduction order/layout can flip the argmin on a near-tie (e.g. cosine
    mode where every unit-norm center has c2 ~ 1), subtracting the padding
    count from a different cluster than the kernel assigned it to
    (ADVICE r4).
    """
    precision = pallas_precision(precision)
    d_pad, n_pad = xt.shape
    k = centers.shape[0]
    if centers.shape[1] != d_pad:
        raise ValueError(f"centers width {centers.shape[1]} != x width {d_pad}")
    k_pad = k + ((-k) % 128)
    ct = jnp.pad(centers.T, ((0, 0), (0, k_pad - k)))  # (d_pad, k_pad)
    c2 = jnp.sum(ct * ct, axis=0, keepdims=True)  # (1, k_pad)
    # Padded center columns are all-zero -> c2 = 0 would WIN every argmin.
    # Push them to the finite sentinel so no real row ever lands there
    # (NOT +inf: the "high" path's hi/lo split turns inf into NaN).
    if k_pad > k:
        col = jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
        c2 = jnp.where(col < k, c2, _UNUSED_SCORE)
    if precision not in ("highest", "high", "default"):
        raise ValueError(f"precision must be highest|high|default, got {precision!r}")
    nb = n_pad // block_n

    sums, counts, cost = pl.pallas_call(
        partial(_assign_stats_kernel, precision=precision),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((d_pad, block_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((d_pad, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        interpret=interpret,
    )(xt, ct, c2)
    return sums[:k], counts[0, :k], cost[0, 0], c2[0, :k]


def _packed_geometry(d_pad: int, k: int):
    """(P, dg, kg) for the lane-packed kernel, or None when packing
    cannot help: dg is the per-group feature stride (16/32/64), P = 128
    // dg groups share one contraction, kg = 128 // P score slots per
    group. Packing needs d_pad <= 64 (else the lane tile is already
    well used) and k <= kg (each group's scores must fit its slot)."""
    for dg in (16, 32, 64):
        if d_pad <= dg:
            p = 128 // dg
            if k <= 128 // p:
                return p, dg, 128 // p
            return None
    return None


def packed_feasible(d: int, k: int) -> bool:
    """True when :func:`assign_stats_packed` can run at this (d, k)."""
    return _packed_geometry(d + ((-d) % 8), k) is not None


def _assign_stats_packed_kernel(
    xp_ref, cp_ref, c2p_ref, sums_ref, counts_ref, cost_ref,
    *, precision, groups, kg,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[0, 0] = jnp.float32(0.0)

    xp = xp_ref[:]  # (128, bn): P groups of dg feature sublanes
    bn = xp.shape[1]
    # ONE 128-lane contraction scores all P groups: cp is block-diagonal,
    # so group g's score slot sees only group g's features.
    xc = _dot_prec(
        xp, cp_ref[:], (((0,), (0,)), ((), ())), precision
    )  # (bn, P*kg)
    scores = c2p_ref[:] - 2.0 * xc
    s3 = scores.reshape(bn, groups, kg)
    labels = jnp.argmin(s3, axis=2)  # (bn, groups)
    m = jnp.min(s3, axis=2)
    oh = (
        jax.lax.broadcasted_iota(jnp.int32, s3.shape, 2) == labels[:, :, None]
    ).astype(jnp.float32).reshape(bn, groups * kg)
    # Packed stats GEMM: (P*kg, P*dg) in one tile; only the P diagonal
    # (kg, dg) blocks are wanted — the off-diagonal blocks are the price
    # of the shared contraction and are discarded by the caller.
    if precision == "high":
        xp_hi, xp_lo = _split_hi_lo(xp)
        default = jax.lax.Precision.DEFAULT
        kw = dict(
            dimension_numbers=(((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        sums_ref[:] += jax.lax.dot_general(
            oh, xp_hi, precision=default, **kw
        ) + jax.lax.dot_general(oh, xp_lo, precision=default, **kw)
    else:
        sums_ref[:] += _dot_prec(oh, xp, (((0,), (1,)), ((), ())), precision)
    counts_ref[:] += jnp.sum(oh, axis=0, keepdims=True)
    cost_ref[0, 0] += jnp.sum(xp * xp) + jnp.sum(m)


@partial(jax.jit, static_argnames=("block_n", "precision", "interpret"))
def assign_stats_packed(
    xt: jax.Array,
    centers: jax.Array,
    block_n: int = 4096,
    precision: str = "highest",
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Lane-packed :func:`assign_stats_fused` for small d AND small k.

    At d=16, k<=16 the fused kernel's score contraction uses 16 of 128
    MXU lanes and 16 of 128 output columns — 112 lanes of zeros ride
    along every tile (VERDICT r5 #3). This variant packs P = 128/dg
    INDEPENDENT row blocks into one contraction: X regroups to (128,
    n/P) with each group's d features at its own sublane offset, the
    centers become a block-diagonal (128, 128) operand, and both the
    score and stats GEMMs cover P row blocks per MXU tile — an
    algebraically identical assignment (same c2 values, same per-group
    argmin) at 1/P the tile count. Same contract as
    :func:`assign_stats_fused` (raw stats INCLUDING padding rows).

    Measured verdict lives in BASELINE.md ("KMeans lane packing"): the
    tile-count win is a TPU systolic-array property; on this CPU-only
    environment the packed shapes run the same algebraic FLOPs, so the
    entry records the measured CPU number and the model, not a claimed
    TPU speedup.
    """
    precision = pallas_precision(precision)
    d_pad, n_pad = xt.shape
    k = centers.shape[0]
    if centers.shape[1] != d_pad:
        raise ValueError(f"centers width {centers.shape[1]} != x width {d_pad}")
    geom = _packed_geometry(d_pad, k)
    if geom is None:
        raise ValueError(f"packing infeasible at d_pad={d_pad}, k={k}")
    p, dg, kg = geom
    if n_pad % p:
        raise ValueError(f"n_pad {n_pad} not divisible by pack factor {p}")
    np_rows = n_pad // p
    if np_rows % block_n:
        block_n = max(
            128, min(block_n, (np_rows // max(np_rows // block_n, 1)))
        )
        while np_rows % block_n:
            block_n //= 2
        if block_n < 8:
            raise ValueError(f"no block size divides {np_rows}")
    if precision not in ("highest", "high", "default"):
        raise ValueError(f"precision must be highest|high|default, got {precision!r}")

    # (d_pad, P*np) -> (P, d_pad, np) -> zero-pad each group to dg
    # sublanes -> (128, np): group g's features live at sublane g*dg.
    xp = xt.reshape(d_pad, p, np_rows).transpose(1, 0, 2)
    xp = jnp.pad(xp, ((0, 0), (0, dg - d_pad), (0, 0))).reshape(
        p * dg, np_rows
    )
    ct = centers.T  # (d_pad, k)
    c2_col = jnp.sum(ct * ct, axis=0)  # (k,) — same reduction as fused
    # Block-diagonal centers: group g rows [g*dg, g*dg+d_pad) x cols
    # [g*kg, g*kg+k).
    eye = jnp.eye(p, dtype=xt.dtype)  # (P, P)
    cp = jnp.einsum("ab,dk->adbk", eye, jnp.pad(ct, ((0, dg - d_pad), (0, kg - k)))).reshape(p * dg, p * kg)
    # Unused score slots (k..kg) push to the finite sentinel so no row
    # lands there (NOT +inf: the "high" split turns inf into NaN).
    slot = jax.lax.broadcasted_iota(jnp.int32, (kg,), 0)
    c2_slot = jnp.where(slot < k, jnp.pad(c2_col, (0, kg - k)), _UNUSED_SCORE)
    c2p = jnp.tile(c2_slot, p)[None, :]  # (1, 128)

    nb = np_rows // block_n
    sums, counts, cost = pl.pallas_call(
        partial(
            _assign_stats_packed_kernel,
            precision=precision, groups=p, kg=kg,
        ),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((p * dg, block_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((p * dg, p * kg), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p * kg), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((p * kg, p * dg), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, p * kg), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((p * kg, p * dg), jnp.float32),
            jax.ShapeDtypeStruct((1, p * kg), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        interpret=interpret,
    )(xp, cp, c2p)

    # Keep the P diagonal (kg, dg) blocks; the off-diagonal blocks are
    # cross-group garbage from the shared stats tile.
    sums4 = sums.reshape(p, kg, p, dg)
    sums_kd = sum(sums4[g, :, g, :] for g in range(p))  # (kg, dg)
    counts_k = jnp.sum(counts.reshape(p, kg), axis=0)
    return (
        sums_kd[:k, :d_pad],
        counts_k[:k],
        cost[0, 0],
        c2_slot[:k],
    )


def fused_feasible(d: int, k: int) -> bool:
    """True when the kernel's fixed VMEM residents (centers + c2 + the
    (k, d) accumulator) plus one minimum 128-column block fit the budget.
    The KMeans backend resolver consults this — auto falls back to XLA,
    an explicit backend='fused' raises."""
    return auto_block_n(d, k) is not None


def auto_block_n(d: int, k: int):
    """Row-block size that keeps the kernel's VMEM residents (x tile
    double-buffered + scores + one-hot + split scratch) within ~10 MB,
    or None when even the minimum 128-column block would not fit (very
    wide d x large k — the XLA path handles those)."""
    d_pad = d + ((-d) % 8)
    k_pad = k + ((-k) % 128)
    per_col = 4 * d_pad + 2 * k_pad  # f32 elements per block column
    fixed = 2 * d_pad * k_pad + k_pad * d_pad + k_pad  # ct + sums + c2
    budget_elems = (10 << 20) // 4 - fixed
    bn = budget_elems // per_col if budget_elems > 0 else 0
    if bn < 128:
        return None
    return (min(8192, bn) // 128) * 128


def pad_transposed(x: jax.Array, block_n: int = 4096) -> Tuple[jax.Array, int]:
    """(n, d) -> zero-padded (d_pad, n_pad) transposed copy for the fused
    kernel (one extra HBM round trip of X, amortized over all Lloyd
    iterations). Returns (xt, n_true)."""
    n, d = x.shape
    d_pad = (-d) % 8
    n_pad = (-n) % block_n
    xt = x.T
    if d_pad or n_pad:
        xt = jnp.pad(xt, ((0, d_pad), (0, n_pad)))
    return xt, n


@partial(
    jax.jit,
    static_argnames=(
        "n_true", "max_iter", "block_n", "precision", "cosine", "interpret",
        "packed",
    ),
)
def lloyd_fused(
    xt: jax.Array,
    n_true: int,
    init_centers: jax.Array,
    max_iter: int = 20,
    tol: float = 1e-4,
    block_n: int = 4096,
    precision: str = "highest",
    cosine: bool = False,
    interpret: bool = False,
    packed: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full Lloyd fit on the fused kernel: (centers, cost, n_iter).

    Same convergence semantics as :func:`ops.kmeans.lloyd` (movement tol,
    empty clusters keep their center, final cost at converged centers).
    ``xt`` comes from :func:`pad_transposed`; ``init_centers`` is (k, d)
    and is zero-padded to the kernel width internally. The returned
    centers carry the same d_pad width — slice ``[:, :d]`` outside.

    Padding correction: the n_pad zero columns all score argmin(c2) with
    distance min(c2) and contribute zero to sums — subtracted in closed
    form each pass, so results are EXACTLY the masked formulation's.

    ``packed=True`` routes each pass through
    :func:`assign_stats_packed` (lane-packed contraction for small d and
    k; caller checks :func:`packed_feasible` first). Padding rows behave
    identically — each group's unused score slots are +inf, so zero rows
    land on the global argmin(c2) in every group.
    """
    d_pad = xt.shape[0]
    n_pad_rows = xt.shape[1] - n_true
    k = init_centers.shape[0]
    init = jnp.pad(
        init_centers.astype(jnp.float32),
        ((0, 0), (0, d_pad - init_centers.shape[1])),
    )

    def correct(stats):
        # c2 comes back from the kernel call — the same buffer the scores
        # were computed against, so this argmin agrees with the kernel's
        # padding-row assignment even on exact ties (ADVICE r4).
        sums, counts, cost, c2 = stats
        pad_label = jnp.argmin(c2)
        counts = counts.at[pad_label].add(-jnp.float32(n_pad_rows))
        cost = cost - n_pad_rows * c2[pad_label]
        return sums, counts, cost

    assign = assign_stats_packed if packed else assign_stats_fused

    def step(centers):
        stats = assign(
            xt, centers, block_n=block_n, precision=precision,
            interpret=interpret,
        )
        sums, counts, cost = correct(stats)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
        )
        if cosine:
            norms = jnp.sqrt(jnp.sum(new_centers * new_centers, axis=1, keepdims=True))
            new_centers = new_centers / jnp.maximum(norms, 1e-12)
        return new_centers, cost

    def cond(state):
        _, moved, it, _ = state
        return jnp.logical_and(moved > tol * tol, it < max_iter)

    def body(state):
        centers, _, it, _ = state
        new_centers, cost = step(centers)
        moved = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
        return new_centers, moved, it + 1, cost

    state0 = (
        init,
        jnp.asarray(jnp.inf, jnp.float32),
        0,
        jnp.asarray(0.0, jnp.float32),
    )
    centers, _, n_iter, _ = jax.lax.while_loop(cond, body, state0)
    # Final cost at the converged centers (lloyd parity).
    _, _, cost = correct(
        assign(
            xt, centers, block_n=block_n, precision=precision,
            interpret=interpret,
        )
    )
    return centers, cost, n_iter
