"""Pallas TPU kernel: fused center + covariance accumulation.

The hot op of PCA fit (SURVEY.md §3.1 hot loops 1+2: per-row centering +
C = BᵀB). The XLA scan version (ops.covariance.centered_gram_blocked) writes
each centered block back to HBM before the matmul reads it; this kernel keeps
the centered tile AND the (d, d) accumulator in VMEM — the only HBM traffic
is the single streaming read of X. Grid steps run sequentially on a TPU
core, so the revisited accumulator block is race-free.

Layout constraints (pallas_guide.md tiling): d padded to a lane multiple
(128), row tiles padded to sublane multiples; padded rows are filled with the
mean so their centered contribution is exactly zero (same trick as the scan
path), padded columns with zeros.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _cov_kernel(x_ref, mean_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    b = x_ref[:] - mean_ref[:]
    # bᵀ b on the MXU: contract the row (tile) dimension of both operands.
    # precision=HIGHEST: without it f32 operands take the single-pass bf16
    # MXU route on real hardware (~1e-3 relative error), far below the
    # 1e-5 oracle bar — and invisible to interpret-mode tests.
    acc_ref[:] += jax.lax.dot_general(
        b,
        b,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=acc_ref.dtype,
        precision=jax.lax.Precision.HIGHEST,
    )


@partial(jax.jit, static_argnames=("block_rows", "interpret"))
def centered_gram_pallas(
    x: jax.Array,
    mean: jax.Array,
    block_rows: int = 1024,
    interpret: bool = False,
) -> jax.Array:
    """(x − mean)ᵀ(x − mean) with centering fused into the streaming kernel.

    ``interpret=True`` runs the Pallas interpreter (CPU testing). Output is
    (d, d) in x.dtype; accumulation is fp32 (or the input dtype if wider).
    """
    n, d = x.shape
    if n == 0:
        return jnp.zeros((d, d), dtype=x.dtype)
    # Pad d to a lane multiple and rows to a whole number of tiles.
    d_pad = (-d) % 128
    # VMEM budget: x tile (double-buffered) + centered temp + the HIGHEST-
    # precision dot's multi-pass scratch (6 bf16 passes keep ~6 tile-sized
    # operand splits live) + (dp, dp) accumulator, all within the ~16 MB
    # scoped limit. Empirically on v5e at d=1024 a 256-row tile compiles and
    # 512 does not, which matches an 8*tile + acc model against a 12 MB
    # budget — so clamp block_rows to (12 MB/4 - dp^2) / (8*dp), keeping a
    # sublane multiple.
    dp_ = d + d_pad
    budget_elems = (12 << 20) // 4
    max_block = (budget_elems - dp_ * dp_) // (8 * dp_)
    if max_block < 8:
        raise ValueError(
            f"d={d} needs a ({dp_}, {dp_}) VMEM accumulator that exceeds the "
            "~16 MB VMEM budget; use ops.covariance.centered_gram_blocked"
        )
    # Sublane alignment applies to the user-passed tile size too, not just
    # the VMEM clamp — Mosaic rejects non-final block tiles that are not a
    # multiple of 8 rows.
    block_rows = max(8, (int(min(block_rows, max_block)) // 8) * 8)
    nb = -(-n // block_rows)
    n_pad = nb * block_rows - n
    mean_p = jnp.pad(mean, (0, d_pad)) if d_pad else mean
    x_p = jnp.pad(x, ((0, 0), (0, d_pad))) if d_pad else x
    if n_pad:
        x_p = jnp.concatenate(
            [x_p, jnp.broadcast_to(mean_p, (n_pad, d + d_pad))], axis=0
        )
    dp = d + d_pad
    acc_dtype = x.dtype if jnp.finfo(x.dtype).bits >= 32 else jnp.float32

    out = pl.pallas_call(
        _cov_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, dp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((dp,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((dp, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((dp, dp), acc_dtype),
        interpret=interpret,
    )(x_p, mean_p)
    return out[:d, :d].astype(x.dtype)
