"""Randomized PCA — range-finder GEMMs for the wide-feature regime.

The reference's scaling axis is the feature dimension n (SURVEY.md §5: its
packed spr path caps at n <= 65535 columns, and the GEMM path requires the
(d, d) covariance to fit on one device). The covariance route is O(n d^2)
FLOPs and O(d^2) memory — at d ~ 10^5 the d x d Gram alone is 40 GB.
Randomized subspace iteration (Halko-Martinsson-Tropp) sidesteps both: two
streaming GEMM passes over X per power iteration with an (n, l) sketch,
l = k + oversample << d, and a final small SVD.

TPU-first details:
  - Orthonormalization is Cholesky-QR2 — two (l, l) Gram matmuls + two
    triangular solves — instead of Householder QR, which XLA would run as
    a sequential panel algorithm. CQR2's second pass restores the
    orthogonality CQR1 loses at fp32 (condition-squaring), and everything
    is MXU work.
  - Mean centering is FOLDED into the GEMMs (rank-one corrections), so the
    centered matrix is never materialized.
  - The total variance (denominator of explainedVariance) is exact — the
    trace of the covariance from column moments — so the ratios match the
    covariance path, not just the top-l approximation of it.
  - Deterministic: fixed PRNG key, sign-flip on the components.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.eigh import sign_flip
from spark_rapids_ml_tpu.ops.linalg import _dot_precision


def _chol_qr2(y: jax.Array, prec) -> jax.Array:
    """Orthonormalize the columns of (n, l) via two Cholesky-QR passes."""
    eps = jnp.finfo(y.dtype).eps

    def once(y):
        g = jnp.matmul(y.T, y, precision=prec)
        # Tiny ridge: guards the Cholesky when the sketch is near-rank-
        # deficient (e.g. data with fewer than l independent directions).
        g = g + (eps * jnp.trace(g)) * jnp.eye(g.shape[0], dtype=y.dtype)
        r = jnp.linalg.cholesky(g).T  # upper
        return jax.scipy.linalg.solve_triangular(r.T, y.T, lower=True).T

    return once(once(y))


@partial(
    jax.jit, static_argnames=("k", "oversample", "power_iters", "precision", "center")
)
def randomized_pca(
    x: jax.Array,
    k: int,
    key: jax.Array,
    oversample: int = 10,
    power_iters: int = 2,
    precision: str = "highest",
    center: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k principal components without forming the covariance.

    Returns (components (d, k), explained_variance_ratio (k,), mean (d,)).
    ``power_iters`` subspace iterations sharpen the spectrum (q=2 is the
    standard accuracy/cost point); each costs two GEMM passes over x.
    ``center=False`` runs second-moment PCA (the meanCentering=False
    semantics of the covariance path).
    """
    n, d = x.shape
    if k > min(n, d):
        raise ValueError(
            f"randomized PCA needs k <= min(n_rows, n_features) = "
            f"{min(n, d)}, got k={k}"
        )
    l = min(k + oversample, d, n)
    prec = _dot_precision(precision)
    dtype = x.dtype

    mean = jnp.mean(x, axis=0) if center else jnp.zeros((d,), dtype)

    def center_matmul(v):  # Xc @ v without materializing Xc
        return jnp.matmul(x, v, precision=prec) - jnp.outer(
            jnp.ones((n,), dtype), mean @ v
        )

    def center_rmatmul(u):  # Xc^T @ u
        return jnp.matmul(x.T, u, precision=prec) - jnp.outer(
            mean, jnp.sum(u, axis=0)
        )

    omega = jax.random.normal(key, (d, l), dtype=dtype)
    y = center_matmul(omega)  # (n, l)
    q = _chol_qr2(y, prec)
    for _ in range(power_iters):  # static unroll; q small
        z = _chol_qr2(center_rmatmul(q), prec)  # (d, l)
        q = _chol_qr2(center_matmul(z), prec)

    b = center_rmatmul(q).T  # (l, d): Q^T Xc
    # SVD of the small projected matrix: right singular vectors approximate
    # the top principal directions.
    _, s, vt = jnp.linalg.svd(b, full_matrices=False)
    comps = sign_flip(vt[:k].T)  # (d, k)

    # Exact total variance from a centered two-pass trace (the
    # explainedVariance denominator must cover ALL directions, not just the
    # sketched l). E[x^2] - mean^2 would cancel catastrophically in fp32
    # for large-offset features; the centered sum does not.
    total_var = jnp.sum((x - mean) ** 2) / jnp.maximum(n - 1, 1)
    explained = (s[:k] ** 2) / jnp.maximum(n - 1, 1)
    ratio = explained / jnp.maximum(total_var, jnp.finfo(dtype).tiny)
    return comps, ratio, mean
