"""Randomized PCA — range-finder GEMMs for the wide-feature regime.

The reference's scaling axis is the feature dimension n (SURVEY.md §5: its
packed spr path caps at n <= 65535 columns, and the GEMM path requires the
(d, d) covariance to fit on one device). The covariance route is O(n d^2)
FLOPs and O(d^2) memory — at d ~ 10^5 the d x d Gram alone is 40 GB.
Randomized subspace iteration (Halko-Martinsson-Tropp) sidesteps both: two
streaming GEMM passes over X per power iteration with an (n, l) sketch,
l = k + oversample << d, and a final small SVD.

TPU-first details:
  - Orthonormalization is Cholesky-QR2 — two (l, l) Gram matmuls + two
    triangular solves — instead of Householder QR, which XLA would run as
    a sequential panel algorithm. CQR2's second pass restores the
    orthogonality CQR1 loses at fp32 (condition-squaring), and everything
    is MXU work.
  - Mean centering is FOLDED into the GEMMs (rank-one corrections), so the
    centered matrix is never materialized.
  - The total variance (denominator of explainedVariance) is exact — the
    trace of the covariance from column moments — so the ratios match the
    covariance path, not just the top-l approximation of it.
  - Deterministic: fixed PRNG key, sign-flip on the components.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.eigh import sign_flip
from spark_rapids_ml_tpu.ops.precision import make_dot


def _chol_qr2(y: jax.Array, dot) -> jax.Array:
    """Orthonormalize the columns of (n, l) via two Cholesky-QR passes.
    ``dot`` is the policy-resolved matmul (ops.precision.make_dot)."""
    eps = jnp.finfo(y.dtype).eps

    def once(y):
        g = dot(y.T, y)
        # Tiny ridge: guards the Cholesky when the sketch is near-rank-
        # deficient (e.g. data with fewer than l independent directions).
        g = g + (eps * jnp.trace(g)) * jnp.eye(g.shape[0], dtype=y.dtype)
        r = jnp.linalg.cholesky(g).T  # upper
        return jax.scipy.linalg.solve_triangular(r.T, y.T, lower=True).T

    return once(once(y))


@partial(
    jax.jit,
    static_argnames=("k", "oversample", "power_iters", "precision", "center"),
)
def randomized_pca(
    x: jax.Array,
    k: int,
    key: jax.Array,
    oversample: int = 10,
    power_iters: int = 2,
    precision: str = "highest",
    center: bool = True,
    mask: jax.Array | None = None,
    n_true: jax.Array | int | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k principal components without forming the covariance.

    Returns (components (d, k), explained_variance_ratio (k,), mean (d,)).
    ``power_iters`` subspace iterations sharpen the spectrum (q=2 is the
    standard accuracy/cost point); each costs two GEMM passes over x.
    ``center=False`` runs second-moment PCA (the meanCentering=False
    semantics of the covariance path).

    ``mask``/``n_true`` make the sketch MESH-READY (VERDICT r2 #6): a
    row-sharded mesh placement zero-pads rows, and the mask keeps those
    rows out of the mean, the sketch panels, and the total variance. All
    ops are tall-skinny GEMMs + (l, l) work, so under GSPMD a sharded
    ``x`` runs with one psum per rmatmul and NO (d, d) covariance on any
    device — the sketch shards exactly like the covariance does.
    """
    n, d = x.shape
    if k > min(n, d):
        raise ValueError(
            f"randomized PCA needs k <= min(n_rows, n_features) = "
            f"{min(n, d)}, got k={k}"
        )
    l = min(k + oversample, d, n)
    dot = make_dot(precision)
    dtype = x.dtype
    if n_true is None:
        n_true = n
    n_eff = jnp.asarray(n_true, dtype=dtype)

    # Padded rows are zero ALREADY (placement contract), so plain column
    # sums are exact; the mask matters for anything that SUBTRACTS the
    # mean (a padded row would otherwise contribute (0 - mean)).
    mean = jnp.sum(x, axis=0) / n_eff if center else jnp.zeros((d,), dtype)

    def apply_mask(u):
        return u if mask is None else u * mask[:, None]

    def center_matmul(v):  # Xc @ v without materializing Xc, padded rows 0
        return apply_mask(
            dot(x, v)
            - jnp.outer(jnp.ones((n,), dtype), mean @ v)
        )

    def center_rmatmul(u):  # Xc^T @ u for ALREADY-masked u
        return dot(x.T, u) - jnp.outer(
            mean, jnp.sum(u, axis=0)
        )

    omega = jax.random.normal(key, (d, l), dtype=dtype)
    y = center_matmul(omega)  # (n, l)
    q = _chol_qr2(y, dot)
    for _ in range(power_iters):  # static unroll; q small
        z = _chol_qr2(center_rmatmul(q), dot)  # (d, l)
        q = _chol_qr2(center_matmul(z), dot)

    b = center_rmatmul(q).T  # (l, d): Q^T Xc
    # SVD of the small projected matrix: right singular vectors approximate
    # the top principal directions.
    _, s, vt = jnp.linalg.svd(b, full_matrices=False)
    comps = sign_flip(vt[:k].T)  # (d, k)

    # Exact total variance from a centered two-pass trace (the
    # explainedVariance denominator must cover ALL directions, not just the
    # sketched l). E[x^2] - mean^2 would cancel catastrophically in fp32
    # for large-offset features; the centered sum does not. Padded rows
    # would each contribute ||mean||^2 — mask them.
    sq = jnp.sum((x - mean) ** 2, axis=1)
    if mask is not None:
        sq = sq * mask
    total_var = jnp.sum(sq) / jnp.maximum(n_eff - 1, 1)
    explained = (s[:k] ** 2) / jnp.maximum(n_eff - 1, 1)
    ratio = explained / jnp.maximum(total_var, jnp.finfo(dtype).tiny)
    return comps, ratio, mean


@partial(jax.jit, static_argnames=("precision",))
def _gram_power_block(z, acc, rsum, xb, mean, precision="highest"):
    """One block's contribution to Xcᵀ(Xc·Z): two tall-skinny GEMMs, no
    (d, d) anything. Returns updated ``(acc (d, l), rsum scalar-vector)``
    where ``rsum`` accumulates Σ rows of Xc·Z (the rank-one mean
    correction of the rmatmul)."""
    dot = make_dot(precision)
    t = dot(xb, z) - jnp.outer(
        jnp.ones((xb.shape[0],), xb.dtype), mean @ z
    )  # (b, l) = Xcb Z
    return (
        acc + dot(xb.T, t),
        rsum + jnp.sum(t, axis=0),
    )


@partial(jax.jit, static_argnames=("precision",))
def _sketch_gram_block(z, g, xb, mean, precision="highest"):
    """One block's contribution to (Xc·Z)ᵀ(Xc·Z) — the (l, l) Rayleigh-
    Ritz Gram of the converged sketch basis."""
    dot = make_dot(precision)
    t = dot(xb, z) - jnp.outer(
        jnp.ones((xb.shape[0],), xb.dtype), mean @ z
    )
    return g + dot(t.T, t)


def randomized_pca_streaming(
    make_blocks,
    k: int,
    key: jax.Array,
    oversample: int = 10,
    power_iters: int = 2,
    precision: str = "highest",
    center: bool = True,
    dtype=None,
    device=None,
):
    """Top-k PCA over a RE-ITERABLE block stream at O(d·l + block) memory
    — the wide-feature regime with NO (d, d) covariance and NO (n, l)
    sketch panel anywhere (VERDICT r2 #6: beat the reference's
    RapidsRowMatrix.scala:66-68 cap AND the GEMM path's one-device
    (d, d) requirement simultaneously).

    Subspace iteration on the implicit Gram: per pass, each block
    contributes Xcᵦᵀ(Xcᵦ·Z) via two tall-skinny MXU GEMMs (the (d, l)
    state is the only cross-block memory), then CholeskyQR2
    re-orthonormalizes. A final pass builds the (l, l) Rayleigh–Ritz Gram
    whose eigensolve yields Ritz values (exact explained-variance ratios
    against the streamed total variance) and components ``Z·U``.

    ``make_blocks`` is a zero-arg callable returning a fresh block
    iterator — multi-pass algorithms need re-iterable sources (an
    ``NpyBlockReader``, an iterator factory, a list of blocks). Passes:
    1 (moments) + power_iters (gram-power) + 1 (Rayleigh–Ritz).
    ``device`` pins the block GEMMs (the gpuId semantics); blocks are
    zero/mean-padded to power-of-two row buckets so ragged streams reuse
    a handful of compiled kernels instead of one per distinct height.

    Returns ``(components (d, k), explained_variance_ratio (k,),
    mean (d,), n_rows)``.
    """
    import numpy as np

    from spark_rapids_ml_tpu.core.data import _block_to_dense

    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    if device is None:
        device = jax.local_devices()[0]

    # Pass 0 — moments: mean and centered total variance via a shifted
    # fp64 host accumulation (exact; the shift kills the cancellation a
    # raw E[x²] − mean² would suffer).
    shift = None
    s_sum = None
    sq_sum = 0.0
    n = 0
    d = None
    for blk in make_blocks():
        b = _block_to_dense(blk)
        if b.shape[0] == 0:
            continue
        if shift is None:
            d = b.shape[1]
            shift = b.mean(axis=0) if center else np.zeros(d)
            s_sum = np.zeros(d)
        bs = b - shift
        s_sum += bs.sum(axis=0)
        sq_sum += float((bs * bs).sum())
        n += b.shape[0]
    if n < 2:
        raise ValueError(f"need at least 2 rows, got {n}")
    if k > min(n, d):
        raise ValueError(
            f"randomized PCA needs k <= min(n_rows, n_features) = "
            f"{min(n, d)}, got k={k}"
        )
    delta = s_sum / n
    mean_h = shift + delta if center else np.zeros(d)
    # Σ‖x − mean‖² = Σ‖x − shift‖² − n‖δ‖² (the shifted-trace identity).
    # With center=False the Ritz values are RAW second moments, so the
    # denominator must be the raw trace — no mean-energy subtraction.
    raw = sq_sum - (n * float(delta @ delta) if center else 0.0)
    total_var = max(raw, 0.0) / (n - 1)

    l = min(k + oversample, d, n)
    dot = make_dot(precision)
    mean_np = (mean_h if center else np.zeros(d)).astype(
        np.dtype(dtype), copy=False
    )
    mean_dev = jax.device_put(mean_np, device)
    z = jax.device_put(jax.random.normal(key, (d, l), dtype=dtype), device)

    def bucketed(b):
        """Pad rows to a power-of-two bucket WITH MEAN ROWS: a mean row
        centers to zero, so it contributes nothing to any accumulator —
        and ragged streams hit a handful of compiled shapes."""
        rows = b.shape[0]
        bucket = max(128, 1 << (rows - 1).bit_length())
        if bucket > rows:
            b = np.concatenate(
                [b, np.broadcast_to(mean_np, (bucket - rows, d))]
            )
        return jax.device_put(b.astype(np.dtype(dtype), copy=False), device)

    # Power passes: Z ← orth(Xcᵀ(Xc·Z)), one streamed pass each.
    for _ in range(max(power_iters, 1)):
        acc = jax.device_put(jnp.zeros((d, l), dtype=dtype), device)
        rsum = jax.device_put(jnp.zeros((l,), dtype=dtype), device)
        for blk in make_blocks():
            b = _block_to_dense(blk)
            if b.shape[0] == 0:
                continue
            acc, rsum = _gram_power_block(
                z, acc, rsum, bucketed(b), mean_dev, precision=precision
            )
        # Complete the rmatmul's mean correction: Xcᵀ = Xᵀ − mean·1ᵀ, so
        # Xcᵀ(XcZ) = Σ Xᵦᵀtᵦ − mean·Σ rows(t).
        acc = acc - jnp.outer(mean_dev, rsum)
        z = _chol_qr2(acc, dot)

    # Rayleigh–Ritz pass: G = Zᵀ Xcᵀ Xc Z streamed as (l, l).
    g = jax.device_put(jnp.zeros((l, l), dtype=dtype), device)
    for blk in make_blocks():
        b = _block_to_dense(blk)
        if b.shape[0] == 0:
            continue
        g = _sketch_gram_block(z, g, bucketed(b), mean_dev, precision=precision)
    w, u = jnp.linalg.eigh(g / (n - 1))  # ascending
    w = jnp.maximum(w[::-1][:k], 0)
    comps = sign_flip(dot(z, u[:, ::-1][:, :k]))
    ratio = np.asarray(w, dtype=np.float64) / max(total_var, 1e-300)
    return np.asarray(comps), ratio, mean_h, n
