"""Device-side evaluator kernels — metrics at dataset scale.

The host evaluators (evaluation.py) collect both columns to numpy, which
is right for validation folds but not for scoring 100M-row outputs
(VERDICT r1 weak item 7: "AUC sort on host"). These jitted twins keep the
reduction on the accelerator: sorts/cumsums for AUC, a bincount confusion
matrix for multiclass, plain reductions for regression — the evaluators
route here automatically for device-resident or large inputs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def regression_metrics_device(y: jax.Array, p: jax.Array):
    """(rmse, mse, mae, r2) — one fused reduction pass."""
    err = y - p
    mse = jnp.mean(err * err)
    mae = jnp.mean(jnp.abs(err))
    y_mean = jnp.mean(y)
    ss_tot = jnp.sum((y - y_mean) ** 2)
    r2 = jnp.where(ss_tot > 0, 1.0 - jnp.sum(err * err) / ss_tot, 0.0)
    return jnp.sqrt(mse), mse, mae, r2


@partial(jax.jit, static_argnames=("n_classes",))
def confusion_matrix_device(y: jax.Array, p: jax.Array, n_classes: int):
    """(C, C) confusion counts via ONE bincount of the composite label —
    no (n, C) one-hot materialization."""
    comp = y.astype(jnp.int32) * n_classes + p.astype(jnp.int32)
    return jnp.bincount(comp, length=n_classes * n_classes).reshape(
        n_classes, n_classes
    )


def multiclass_metrics_device(y, p, n_classes: int):
    """{accuracy, f1, weightedPrecision, weightedRecall} from the device
    confusion matrix (host math on the tiny (C, C) result)."""
    import numpy as np

    cm = np.asarray(confusion_matrix_device(y, p, n_classes), dtype=np.float64)
    n = cm.sum()
    tp = np.diag(cm)
    per_actual = cm.sum(axis=1)  # rows: true class counts
    per_pred = cm.sum(axis=0)
    weights = per_actual / n
    prec = np.where(per_pred > 0, tp / np.maximum(per_pred, 1), 0.0)
    rec = np.where(per_actual > 0, tp / np.maximum(per_actual, 1), 0.0)
    f1 = np.where(prec + rec > 0, 2 * prec * rec / np.maximum(prec + rec, 1e-300), 0.0)
    return {
        "accuracy": float(tp.sum() / n),
        "f1": float(weights @ f1),
        "weightedPrecision": float(weights @ prec),
        "weightedRecall": float(weights @ rec),
    }


@partial(jax.jit, static_argnames=("metric",))
def binary_auc_device(y: jax.Array, s: jax.Array, metric: str = "areaUnderROC"):
    """Tie-grouped AUC (ROC or PR) — sort + cumsums on the accelerator,
    the same tie treatment as the host evaluator (one curve point per
    distinct threshold, trapezoid through ties)."""
    order = jnp.argsort(-s, stable=True)
    y_sorted = y[order]
    s_sorted = s[order]
    # Counts in int32: exact to 2^31 rows (f32 cumsums would silently
    # round odd counts past 2^24 — the very scale this path exists for).
    is_pos = (y_sorted == 1).astype(jnp.int32)
    n_pos = jnp.sum(is_pos).astype(s.dtype)
    n_neg = (y_sorted.shape[0] - jnp.sum(is_pos)).astype(s.dtype)
    tp_cum = jnp.cumsum(is_pos)
    fp_cum = jnp.cumsum(1 - is_pos)
    distinct = jnp.concatenate(
        [s_sorted[1:] != s_sorted[:-1], jnp.array([True])]
    )
    # Static shapes: nonzero packs the kept (per-distinct-threshold)
    # indices at the front; trapezoids past the last kept point mask to 0.
    idx = jnp.nonzero(distinct, size=distinct.shape[0], fill_value=-1)[0]
    valid = idx >= 0
    tp_k = jnp.where(valid, tp_cum[idx], 0).astype(s.dtype)
    fp_k = jnp.where(valid, fp_cum[idx], 0).astype(s.dtype)
    if metric == "areaUnderROC":
        xs = jnp.where(valid, fp_k / jnp.maximum(n_neg, 1), jnp.nan)
        ys = jnp.where(valid, tp_k / jnp.maximum(n_pos, 1), jnp.nan)
        x_prev = jnp.concatenate([jnp.zeros(1, s.dtype), xs[:-1]])
        y_prev = jnp.concatenate([jnp.zeros(1, s.dtype), ys[:-1]])
    else:
        precision = tp_k / jnp.maximum(tp_k + fp_k, 1.0)
        recall = tp_k / jnp.maximum(n_pos, 1)
        xs = jnp.where(valid, recall, jnp.nan)
        ys = jnp.where(valid, precision, jnp.nan)
        x_prev = jnp.concatenate([jnp.zeros(1, s.dtype), xs[:-1]])
        y_prev = jnp.concatenate([jnp.ones(1, s.dtype), ys[:-1]])
    # Carry forward across invalid slots: they sit past the last kept
    # point, where xs/ys are NaN — mask their trapezoids to zero.
    seg = jnp.where(valid, (xs - x_prev) * (ys + y_prev) / 2.0, 0.0)
    auc = jnp.nansum(seg)
    degenerate = jnp.logical_or(n_pos == 0, n_neg == 0)
    return jnp.where(degenerate, 0.0, auc)


__all__ = [
    "regression_metrics_device",
    "confusion_matrix_device",
    "multiclass_metrics_device",
    "binary_auc_device",
]
