"""Device-side evaluator kernels — metrics at dataset scale.

The host evaluators (evaluation.py) collect both columns to numpy, which
is right for validation folds but not for scoring 100M-row outputs
(VERDICT r1 weak item 7: "AUC sort on host"). These jitted twins keep the
reduction on the accelerator: sorts/cumsums for AUC, a bincount confusion
matrix for multiclass, plain reductions for regression — the evaluators
route here automatically for device-resident or large inputs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def regression_metrics_device(y: jax.Array, p: jax.Array):
    """(rmse, mse, mae, r2) — one fused reduction pass."""
    err = y - p
    mse = jnp.mean(err * err)
    mae = jnp.mean(jnp.abs(err))
    y_mean = jnp.mean(y)
    ss_tot = jnp.sum((y - y_mean) ** 2)
    r2 = jnp.where(ss_tot > 0, 1.0 - jnp.sum(err * err) / ss_tot, 0.0)
    return jnp.sqrt(mse), mse, mae, r2


@partial(jax.jit, static_argnames=("n_classes",))
def confusion_matrix_device(y: jax.Array, p: jax.Array, n_classes: int):
    """(C, C) confusion counts via ONE bincount of the composite label —
    no (n, C) one-hot materialization."""
    comp = y.astype(jnp.int32) * n_classes + p.astype(jnp.int32)
    return jnp.bincount(comp, length=n_classes * n_classes).reshape(
        n_classes, n_classes
    )


def multiclass_metrics_device(y, p, n_classes: int):
    """{accuracy, f1, weightedPrecision, weightedRecall} from the device
    confusion matrix (host math on the tiny (C, C) result)."""
    import numpy as np

    cm = np.asarray(confusion_matrix_device(y, p, n_classes), dtype=np.float64)
    n = cm.sum()
    tp = np.diag(cm)
    per_actual = cm.sum(axis=1)  # rows: true class counts
    per_pred = cm.sum(axis=0)
    weights = per_actual / n
    prec = np.where(per_pred > 0, tp / np.maximum(per_pred, 1), 0.0)
    rec = np.where(per_actual > 0, tp / np.maximum(per_actual, 1), 0.0)
    f1 = np.where(prec + rec > 0, 2 * prec * rec / np.maximum(prec + rec, 1e-300), 0.0)
    return {
        "accuracy": float(tp.sum() / n),
        "f1": float(weights @ f1),
        "weightedPrecision": float(weights @ prec),
        "weightedRecall": float(weights @ rec),
    }


def binary_auc_device(y: jax.Array, s: jax.Array, metric: str = "areaUnderROC"):
    """Tie-grouped AUC (ROC or PR) — ONE variadic sort + cumulative
    scans on the accelerator, the same tie treatment as the host
    evaluator (one curve point per distinct threshold, trapezoid
    through ties).

    Two sort-attack ideas, measured in BASELINE.md's "AUC sort
    shoot-out": (1) instead of ``argsort`` + label/score gathers, sort
    the label ALONG WITH the score key (`lax.sort` with ``num_keys=1``)
    — the n-element random-access gathers disappear and the permutation
    is never materialized; (2) instead of ``nonzero``-packing the
    per-distinct-threshold points (a full-length pack plus two more
    gathers), exploit that tp/fp cumsums are NONDECREASING: a running
    ``cummax`` over the cumsum masked to distinct positions yields the
    previous distinct point's counts in place — every trapezoid reads
    its left edge from a scan, not a gather. A third idea (packing the
    label into the score's mantissa LSB for a single one-operand sort)
    is exactness-rejected there.
    """
    from spark_rapids_ml_tpu.observability import costs

    ledger = costs.active()
    if ledger is not None:
        # Evaluator programs join the cost-ledger gate (CI diffs their
        # analyzed flops/bytes against benchmarks/cost_baseline.json).
        import time

        lkey = costs.record_fallback(
            _binary_auc_jit,
            name="metrics.binary_auc",
            static={"metric": metric},
            args=(y, s),
            lower=lambda: _binary_auc_jit.lower(y, s, metric=metric),
        )
        t0 = time.perf_counter()
        out = _binary_auc_jit(y, s, metric=metric)
        ledger.note_invocation(lkey, time.perf_counter() - t0, rows=int(s.shape[0]))
        return out
    return _binary_auc_jit(y, s, metric=metric)


@partial(jax.jit, static_argnames=("metric",))
def _binary_auc_jit(y: jax.Array, s: jax.Array, metric: str = "areaUnderROC"):
    n = s.shape[0]
    if jax.config.jax_enable_x64 and s.dtype == jnp.float32:
        # Key-packing attack (BASELINE.md shoot-out winner, 5.4x): fold
        # the f32 score through the standard monotone bit transform,
        # append the label as bit 0 of a uint64, and run ONE one-operand
        # sort. Tie groups are exact — the full 32 key bits survive, and
        # tie-grouped AUC reads only group-END cumsums, so the in-group
        # label order (which the packing changes) is immaterial. -0.0
        # canonicalizes to +0.0 first so both zeros share one group.
        # (Scores are assumed NaN-free, as in the host evaluator.)
        # (NOT `s + 0.0`: XLA folds that to `s`, resurrecting -0.0.)
        sz = jnp.where(s == 0, jnp.zeros_like(s), s)
        u = jax.lax.bitcast_convert_type(sz, jnp.uint32)
        flip = jnp.where(
            u >> 31 == 1, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000)
        )
        packed = ((u ^ flip).astype(jnp.uint64) << 1) | y.astype(jnp.uint64)
        srt = jax.lax.sort(packed)[::-1]  # descending score order
        is_pos = (srt & 1).astype(jnp.int32)
        key_desc = srt >> 1
        distinct = jnp.concatenate(
            [key_desc[1:] != key_desc[:-1], jnp.array([True])]
        )
    else:
        # Stable sort on the negated score carries the labels along in
        # the SAME order stable argsort(-s) would — bit-identical
        # grouping; the key output doubles as the threshold sequence.
        neg_sorted, y_sorted = jax.lax.sort((-s, y), num_keys=1, is_stable=True)
        s_desc = -neg_sorted
        is_pos = (y_sorted == 1).astype(jnp.int32)
        distinct = jnp.concatenate(
            [s_desc[1:] != s_desc[:-1], jnp.array([True])]
        )
    # Counts in int32: exact to 2^31 rows (f32 cumsums would silently
    # round odd counts past 2^24 — the very scale this path exists for).
    n_pos_i = jnp.sum(is_pos)
    n_pos = n_pos_i.astype(s.dtype)
    n_neg = (n - n_pos_i).astype(s.dtype)
    tp_cum = jnp.cumsum(is_pos)
    # fp = rank - tp: the second cumsum is free arithmetic.
    fp_cum = jnp.arange(1, n + 1, dtype=jnp.int32) - tp_cum
    # Previous distinct point's counts WITHOUT packing/gathering: mask
    # non-distinct slots to -1, cummax carries the latest distinct
    # cumsum forward (cumsums are nondecreasing, so "latest" == "max"),
    # and a one-slot shift turns "latest at <= i" into "latest BEFORE i".
    neg1 = jnp.full((1,), -1, jnp.int32)
    tp_last = jax.lax.cummax(jnp.where(distinct, tp_cum, -1))
    fp_last = jax.lax.cummax(jnp.where(distinct, fp_cum, -1))
    tp_prev = jnp.concatenate([neg1, tp_last[:-1]])
    fp_prev = jnp.concatenate([neg1, fp_last[:-1]])
    has_prev = tp_prev >= 0
    tp_p = jnp.maximum(tp_prev, 0).astype(s.dtype)
    fp_p = jnp.maximum(fp_prev, 0).astype(s.dtype)
    tp_k = tp_cum.astype(s.dtype)
    fp_k = fp_cum.astype(s.dtype)
    if metric == "areaUnderROC":
        xs = fp_k / jnp.maximum(n_neg, 1)
        ys = tp_k / jnp.maximum(n_pos, 1)
        x_prev = fp_p / jnp.maximum(n_neg, 1)
        y_prev = tp_p / jnp.maximum(n_pos, 1)
    else:
        xs = tp_k / jnp.maximum(n_pos, 1)  # recall
        ys = tp_k / jnp.maximum(tp_k + fp_k, 1.0)  # precision
        x_prev = tp_p / jnp.maximum(n_pos, 1)
        # The curve starts at precision 1.0 (Spark's convention).
        y_prev = jnp.where(
            has_prev, tp_p / jnp.maximum(tp_p + fp_p, 1.0), 1.0
        )
    seg = jnp.where(distinct, (xs - x_prev) * (ys + y_prev) / 2.0, 0.0)
    auc = jnp.sum(seg)
    degenerate = jnp.logical_or(n_pos == 0, n_neg == 0)
    return jnp.where(degenerate, 0.0, auc)


__all__ = [
    "regression_metrics_device",
    "confusion_matrix_device",
    "multiclass_metrics_device",
    "binary_auc_device",
]
