"""KMeans kernels — Lloyd iterations as MXU matmuls.

Beyond-PCA capability (BASELINE.md config 3: "KMeans k=100 on NYC-Taxi 20M
rows — RAFT kmeans -> XLA"). The reference repo itself has no kmeans; the
RAPIDS family's implementation is RAFT's fused distance kernel + cuBLAS. The
TPU formulation keeps everything on the MXU:

  - assignment: pairwise squared distances via the expansion
    ||x||^2 - 2 x C^T + ||c||^2 — one (n,d)x(d,k) matmul, no materialized
    (n,k,d) intermediate;
  - update: cluster sums as one_hot(labels)^T X — a (k,n)x(n,d) matmul —
    so the "scatter-add" is also a systolic-array op;
  - the whole fit is ONE jitted lax.while_loop (movement tolerance + max
    iterations), compiler-friendly static shapes throughout;
  - empty clusters keep their previous center (Spark/RAFT behavior);
  - masked rows (mask=0) support padding for sharded execution: a padded
    row contributes to no cluster and no cost.

Distributed: row-shard x/mask over a mesh data axis and jit with replicated
out-shardings — XLA inserts psum for the segment sums/counts/cost (see
tests/test_kmeans.py::TestDistributed).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.ops.precision import as_dot, make_dot


def _sq_dists(x, centers, x2, dot):
    """(n, k) squared euclidean distances via the Gram expansion.
    ``dot`` is the policy-resolved matmul (ops.precision.make_dot)."""
    c2 = jnp.sum(centers * centers, axis=1)
    xc = dot(x, centers.T)
    return jnp.maximum(x2[:, None] - 2.0 * xc + c2[None, :], 0.0)


@partial(jax.jit, static_argnames=("precision",))
def assign_clusters(x, centers, precision: str = "highest"):
    """Labels + per-row squared distance to the nearest center."""
    dot = make_dot(precision)
    x2 = jnp.sum(x * x, axis=1)
    d2 = _sq_dists(x, centers, x2, dot)
    labels = jnp.argmin(d2, axis=1)
    return labels, jnp.take_along_axis(d2, labels[:, None], axis=1)[:, 0]


def _assign_and_accumulate(xb, mb, x2b, centers, k, dot):
    """Block-local assignment + sufficient stats: (sums (k,d), counts (k),
    cost) for one row block — everything stays block-sized, so XLA fuses
    the distance GEMM, argmin, and one-hot matmul without ever writing an
    (n, k) array to HBM."""
    d2 = _sq_dists(xb, centers, x2b, dot)
    labels = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    one_hot = jax.nn.one_hot(labels, k, dtype=xb.dtype) * mb[:, None]
    sums = dot(one_hot.T, xb)  # (k, d) on MXU
    counts = jnp.sum(one_hot, axis=0)
    cost = jnp.sum(min_d2 * mb)
    return sums, counts, cost


def lloyd_step(x, mask, centers, x2, dot, cosine: bool = False,
               block_rows: int | None = None):
    """One Lloyd iteration. Returns (new_centers, cost).

    ``dot`` is the policy matmul (ops.precision.make_dot); legacy
    spellings (a mode string or a bare ``lax.Precision``) coerce.

    ``cosine``: renormalize updated centers to unit norm (Spark's
    CosineDistanceMeasure.updateClusterCenter) so assignments stay true
    cosine argmins given unit-normalized input rows.

    ``block_rows``: stream rows through a ``lax.scan`` in fixed blocks.
    The unblocked step materializes two (n, k) arrays per iteration —
    ~2·n·k·4 bytes of HBM write+read traffic that dominates the wall clock
    once n·k outgrows the caches; the blocked step's per-iteration traffic
    is one read of x. Rows must already be padded (mask=0) to a multiple
    of ``block_rows`` by the caller-facing :func:`lloyd`.
    """
    dot = as_dot(dot)
    k = centers.shape[0]
    if block_rows is None or x.shape[0] <= block_rows:
        sums, counts, cost = _assign_and_accumulate(x, mask, x2, centers, k, dot)
    else:
        nb = x.shape[0] // block_rows

        def body(carry, blk):
            s, c, j = carry
            xb, mb, x2b = blk
            sb, cb, jb = _assign_and_accumulate(xb, mb, x2b, centers, k, dot)
            return (s + sb, c + cb, j + jb), None

        init = (
            jnp.zeros((k, x.shape[1]), x.dtype),
            jnp.zeros((k,), x.dtype),
            jnp.asarray(0.0, x.dtype),
        )
        (sums, counts, cost), _ = jax.lax.scan(
            body,
            init,
            (
                x.reshape(nb, block_rows, -1),
                mask.reshape(nb, block_rows),
                x2.reshape(nb, block_rows),
            ),
        )
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
    )
    if cosine:
        new_centers = normalize_rows(new_centers)
    return new_centers, cost


def _auto_block_rows(n: int, k: int, data_shards: int, block_rows):
    """Resolve ``block_rows=None`` — shared by the monolithic
    :func:`lloyd` and the segmented :func:`lloyd_resumable` so both
    pick the identical blocking (a prerequisite for bit-identity).

    With ``TPUML_AUTOTUNE=on`` the block is sized from MEASURED HBM
    headroom instead of the static 9 GB guess. Inside the jitted
    :func:`lloyd` this resolves at trace time, so a tuned value freezes
    into the trace keyed on ``block_rows=None`` — stale-but-correct if
    the tune store moves mid-process; ``lloyd_resumable`` re-resolves on
    every fit. Off is the static heuristic bit-for-bit."""
    if block_rows is not None:
        return block_rows
    from spark_rapids_ml_tpu.observability import autotune as _autotune

    tuner = _autotune.active()
    if tuner is not None:
        tuned = tuner.recommend_kmeans_block_rows(n, k, data_shards)
        if tuned is not None:
            return tuned
    # Per-device (n, k) fp32 temporary vs the HBM budget.
    if 4 * n * k // max(data_shards, 1) > 9_000_000_000:
        # Block sized so block*k*4B stays ~1 GB (no larger floor: a
        # floor above this budget would reintroduce the OOM for big k).
        return max(8, (250_000_000 // max(k, 1) // 8) * 8)
    return n + 1  # unblocked


@partial(
    jax.jit,
    static_argnames=("max_iter", "precision", "cosine", "block_rows", "data_shards"),
)
def lloyd(
    x: jax.Array,
    mask: jax.Array,
    init_centers: jax.Array,
    max_iter: int = 20,
    tol: float = 1e-4,
    precision: str = "highest",
    cosine: bool = False,
    block_rows: Optional[int] = None,
    data_shards: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full Lloyd fit: returns (centers, cost, n_iters).

    Convergence criterion matches Spark ML KMeans: stop when no center moves
    more than ``tol`` (euclidean), or at ``max_iter``. With ``cosine``,
    centers stay unit-normalized every iteration (input rows must already be
    unit-normalized), so the returned cost is the cosine-distance potential.

    ``block_rows``: None = auto. The unblocked step is the fast path —
    measured 373M vs 280M row-iters/s at 20M x 16, k=100 on v5e, because
    the distance reduction fuses into the GEMM epilogue and a scan only
    adds sequential dependencies. Blocking exists for MEMORY: once the
    (n, k) one-hot temporary approaches HBM capacity (~9 GB here), rows
    stream through a scan in blocks sized to ~1 GB of temporaries.

    ``data_shards``: number of mesh data-axis shards the rows are spread
    over (1 = single device). The auto threshold compares the PER-DEVICE
    (n/shards, k) temporary against HBM — a row-sharded multi-chip fit must
    not fall onto the sequential blocked path dp times too early.
    """
    dot = make_dot(precision)
    n = x.shape[0]
    k = init_centers.shape[0]
    block_rows = _auto_block_rows(n, k, data_shards, block_rows)
    blocked = n > block_rows
    if blocked:
        pad = (-n) % block_rows
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
            mask = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])
    x2 = jnp.sum(x * x, axis=1)
    br = block_rows if blocked else None

    def cond(state):
        _, moved, it, _ = state
        return jnp.logical_and(moved > tol * tol, it < max_iter)

    def body(state):
        centers, _, it, _ = state
        new_centers, cost = lloyd_step(
            x, mask, centers, x2, dot, cosine=cosine, block_rows=br
        )
        moved = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
        return new_centers, moved, it + 1, cost

    init_state = (init_centers, jnp.asarray(jnp.inf, x.dtype), 0, jnp.asarray(0.0, x.dtype))
    centers, _, n_iter, cost = jax.lax.while_loop(cond, body, init_state)
    # One final cost evaluation against the converged centers.
    _, final_cost = lloyd_step(x, mask, centers, x2, dot, cosine=cosine, block_rows=br)
    return centers, final_cost, n_iter


@partial(
    jax.jit, static_argnames=("max_iter", "every", "precision", "cosine", "block_rows")
)
def _lloyd_segment(
    x, mask, centers, moved, it, cost, tol,
    max_iter: int, every: int,
    precision: str, cosine: bool, block_rows,
):
    """Up to ``every`` Lloyd iterations from an explicit solver state.

    Exactly :func:`lloyd`'s loop body and stopping rule, plus a segment
    budget in the cond — so a sequence of segments executes the SAME
    iteration sequence as the monolithic while_loop, with the full state
    (centers, movement, iteration counter, cost) visible as a pytree
    between segments (the checkpointable form). ``x`` must already be
    padded to the block multiple (the driver owns the padding, once)."""
    dot = make_dot(precision)
    x2 = jnp.sum(x * x, axis=1)
    br = block_rows if (block_rows is not None and x.shape[0] > block_rows) else None

    def cond(state):
        _, moved, it, _, seg = state
        return jnp.logical_and(
            jnp.logical_and(moved > tol * tol, it < max_iter), seg < every
        )

    def body(state):
        centers, _, it, _, seg = state
        new_centers, cost = lloyd_step(
            x, mask, centers, x2, dot, cosine=cosine, block_rows=br
        )
        moved = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
        return new_centers, moved, it + 1, cost, seg + 1

    centers, moved, it, cost, _ = jax.lax.while_loop(
        cond, body, (centers, moved, it, cost, 0)
    )
    return centers, moved, it, cost


@partial(jax.jit, static_argnames=("precision", "cosine", "block_rows"))
def _lloyd_final_cost(x, mask, centers, precision: str, cosine: bool, block_rows):
    """The converged-centers cost evaluation :func:`lloyd` ends with,
    as its own program for the segmented driver."""
    dot = make_dot(precision)
    x2 = jnp.sum(x * x, axis=1)
    br = block_rows if (block_rows is not None and x.shape[0] > block_rows) else None
    _, cost = lloyd_step(x, mask, centers, x2, dot, cosine=cosine, block_rows=br)
    return cost


def lloyd_resumable(
    x: jax.Array,
    mask: jax.Array,
    init_centers: jax.Array,
    checkpointer,
    max_iter: int = 20,
    tol: float = 1e-4,
    precision: str = "highest",
    cosine: bool = False,
    block_rows: Optional[int] = None,
    data_shards: int = 1,
    mesh=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Preemption-tolerant :func:`lloyd`: a host-side outer loop running
    ``checkpointer.every`` iterations per jitted segment, the solver
    state snapshotted asynchronously after each segment, and the fit
    resumed mid-solve from the latest valid checkpoint. Same returns,
    bit-identical centers/cost/iterations (tests/test_checkpoint.py)."""
    from spark_rapids_ml_tpu.robustness.checkpoint import (
        replicate_state_onto_mesh,
        segment_boundary,
    )
    import time

    from spark_rapids_ml_tpu.observability.costs import ledgered_call
    from spark_rapids_ml_tpu.observability.metrics import observe_segment_seconds
    from spark_rapids_ml_tpu.robustness.faults import fault_point
    from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange, bump_counter

    n = x.shape[0]
    k = init_centers.shape[0]
    block_rows = _auto_block_rows(n, k, data_shards, block_rows)
    if n > block_rows:
        pad = (-n) % block_rows
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
            mask = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])

    state = (
        init_centers,
        jnp.asarray(jnp.inf, x.dtype),
        jnp.asarray(0),
        jnp.asarray(0.0, x.dtype),
    )
    restored = checkpointer.restore_latest(template=state)
    if restored is not None:
        _, state = restored
        if mesh is not None:
            state = replicate_state_onto_mesh(state, mesh)

    tol_sq = float(tol) * float(tol)
    while True:
        moved, it = float(state[1]), int(state[2])
        if not (moved > tol_sq and it < max_iter):
            break
        seg_t0 = time.perf_counter()
        with TraceRange("segment kmeans.lloyd", TraceColor.PURPLE):
            fault_point("solver.segment")
            state = ledgered_call(
                _lloyd_segment, (x, mask, *state, tol),
                static=dict(
                    max_iter=max_iter, every=checkpointer.every,
                    precision=precision, cosine=cosine, block_rows=block_rows,
                ),
                name="kmeans.lloyd.segment",
            )
            bump_counter("checkpoint.segments")
            # int() blocks on the segment's device work, so the range —
            # and the histogram — cover dispatch + execution.
            bump_counter("checkpoint.solver_iters", int(state[2]) - it)
        observe_segment_seconds("kmeans.lloyd", time.perf_counter() - seg_t0)
        checkpointer.save_async(int(state[2]), state)
        segment_boundary(checkpointer)

    centers, _, n_iter, _ = state
    cost = _lloyd_final_cost(
        x, mask, centers, precision=precision, cosine=cosine, block_rows=block_rows
    )
    checkpointer.finalize_success()
    return centers, cost, n_iter


@partial(jax.jit, static_argnames=("block_rows", "precision"))
def assign_clusters_blocked(
    x: jax.Array,
    centers: jax.Array,
    block_rows: int = 65536,
    precision: str = "highest",
):
    """Row-blocked :func:`assign_clusters` — the (n, k) distance matrix
    never materializes (one (block, k) buffer per ``lax.map`` step).
    The assignment path for n*k shapes whose full distance matrix would
    blow HBM (e.g. the IVF coarse quantizer at 3M x 2048)."""
    dot = make_dot(precision)
    n = x.shape[0]
    nb = -(-n // block_rows)
    pad = nb * block_rows - n
    xp = jnp.pad(x, ((0, pad), (0, 0)))

    def one(xb):
        x2 = jnp.sum(xb * xb, axis=1)
        d2 = _sq_dists(xb, centers, x2, dot)
        return jnp.argmin(d2, axis=1), jnp.min(d2, axis=1)

    labs, d2s = jax.lax.map(one, xp.reshape(nb, block_rows, -1))
    return labs.reshape(-1)[:n], d2s.reshape(-1)[:n]


@partial(jax.jit, static_argnames=("precision",))
def block_suff_stats(xb: jax.Array, centers: jax.Array, precision: str = "highest"):
    """Lloyd sufficient statistics of ONE full (unmasked) row block against
    fixed centers: (sums (k, d), counts (k,), cost). The streaming fit's
    per-block kernel — accumulating these across blocks and dividing is
    exactly one Lloyd iteration at O(block + k*d) memory."""
    dot = make_dot(precision)
    x2 = jnp.sum(xb * xb, axis=1)
    mb = jnp.ones(xb.shape[0], xb.dtype)
    return _assign_and_accumulate(xb, mb, x2, centers, centers.shape[0], dot)


def reservoir_sample_rows(blocks, cap: int, seed: int, dtype=None):
    """One-pass uniform row reservoir (Algorithm R, vectorized per block).

    Returns ``(sample (min(cap, n), d), n_seen)``. Gives the streaming fit
    an unbiased seeding set without materializing the dataset — the
    standard trick for k-means++ on out-of-core data (cuML seeds its
    streaming k-means from a sample the same way).
    """
    from spark_rapids_ml_tpu.core.data import _block_to_dense

    rng = np.random.default_rng(seed)
    buf = None
    seen = 0
    for blk in blocks:
        b = _block_to_dense(blk, dtype=dtype)
        if b.shape[0] == 0:
            continue
        if buf is None:
            buf = np.empty((cap, b.shape[1]), dtype=b.dtype)
        i = 0
        # Fill phase: the first `cap` rows enter directly.
        if seen < cap:
            take = min(cap - seen, b.shape[0])
            buf[seen : seen + take] = b[:take]
            seen += take
            i = take
        # Replacement phase: global row t replaces slot j ~ U[0, t] if j < cap.
        nb = b.shape[0] - i
        if nb > 0:
            t = seen + np.arange(nb)  # global indices of remaining rows
            js = rng.integers(0, t + 1)
            hit = js < cap
            # Later duplicates into one slot must win in stream order.
            buf[js[hit]] = b[i:][hit]
            seen += nb
    if buf is None:
        raise ValueError("streaming source yielded no rows")
    return buf[: min(cap, seen)], seen


def lloyd_streaming(
    blocks_factory,
    init_centers: jax.Array,
    max_iter: int = 20,
    tol: float = 1e-4,
    precision: str = "highest",
    cosine: bool = False,
    dtype=None,
):
    """Multi-pass Lloyd over a RE-ITERABLE block source at constant memory.

    One data pass per iteration: each host block uploads once, its
    sufficient statistics (:func:`block_suff_stats`) accumulate on device
    (O(k*d) state), and the center update + movement check happen between
    passes. Semantics match :func:`lloyd` (empty clusters keep their
    center, movement-tol stop, final cost evaluated at the converged
    centers). Shares the re-iterable block contract of the streamed PCA
    sketch (linalg/row_matrix.py) — beats the materialize-everything
    ceiling the reference also had (VERDICT r3 #6).
    """
    from spark_rapids_ml_tpu.core.data import _block_to_dense
    from spark_rapids_ml_tpu.robustness.faults import fault_point

    centers = jnp.asarray(init_centers)
    k, d = centers.shape
    np_dtype = np.dtype(dtype) if dtype is not None else np.dtype(centers.dtype)

    def _upload(blk):
        b = _block_to_dense(blk, dtype=np_dtype)
        if b.shape[0] == 0:
            return None
        xb = jnp.asarray(b)
        if cosine:
            xb = normalize_rows(xb)
        return xb

    def blocks_dev():
        # Double-buffered: block k+1 densifies and uploads while block
        # k's suff-stats program runs (serve_stream's overlap pattern via
        # prefetch_blocks); values and order are bit-identical.
        from spark_rapids_ml_tpu.core.serving import prefetch_blocks

        for xb in prefetch_blocks(blocks_factory(), _upload):
            if xb is not None:
                yield xb

    def one_pass(cs):
        fault_point("solver.segment")
        sums = jnp.zeros((k, d), cs.dtype)
        counts = jnp.zeros((k,), cs.dtype)
        cost = jnp.zeros((), cs.dtype)
        for xb in blocks_dev():
            sb, cb, jb = block_suff_stats(xb, cs, precision=precision)
            sums, counts, cost = sums + sb, counts + cb, cost + jb
        return sums, counts, cost

    n_iter = 0
    cost = jnp.zeros((), centers.dtype)
    for n_iter in range(1, max_iter + 1):
        sums, counts, cost = one_pass(centers)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
        )
        if cosine:
            new_centers = normalize_rows(new_centers)
        moved = float(jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1)))
        centers = new_centers
        if moved <= tol * tol:
            break
    # One final cost evaluation against the converged centers (lloyd parity).
    _, _, cost = one_pass(centers)
    return centers, cost, n_iter


@partial(jax.jit, static_argnames=("k", "precision"))
def kmeans_plusplus_init(
    x: jax.Array,
    mask: jax.Array,
    key: jax.Array,
    k: int,
    precision: str = "highest",
) -> jax.Array:
    """Greedy k-means++ seeding, fully on device via lax.fori_loop.

    D^2 sampling (Arthur & Vassilvitskii) with the greedy refinement sklearn
    uses: at each step, draw ``2 + ceil(log2 k)`` candidate rows with
    probability proportional to their squared distance to the nearest chosen
    center (Gumbel-top-t trick — no host sync), then keep the candidate that
    minimizes the resulting total potential. Single-candidate sequential
    k-means++ misses well-separated clusters often enough to matter at
    k >= 20; the greedy variant is the industrial default. Each step is two
    MXU matmuls — (n,d)x(d,k) for current distances and (t,d)x(d,n) for the
    candidate evaluation. Masked (padded) rows are never selected and never
    contribute to the potential.
    """
    dot = make_dot(precision)
    n, d = x.shape
    neg_inf = jnp.asarray(-jnp.inf, x.dtype)
    t = 2 + max(int(np.ceil(np.log2(k))), 0)

    x2 = jnp.sum(x * x, axis=1)
    key0, key_loop = jax.random.split(key)
    # First center: uniform over unmasked rows (Gumbel-max over the mask).
    g0 = jax.random.gumbel(key0, (n,), dtype=x.dtype)
    first = jnp.argmax(jnp.where(mask > 0, g0, neg_inf))
    centers = jnp.zeros((k, d), x.dtype).at[0].set(x[first])
    # min_d2: UNWEIGHTED distance to the nearest chosen center, maintained
    # incrementally. The mask (which may carry fractional weightCol weights)
    # enters only at the sampling probabilities and the potential — scaling
    # min_d2 itself would compound weights across iterations (w^i) and
    # compare weighted against unweighted candidate distances.
    min_d2 = jnp.maximum(x2 - 2.0 * dot(x, x[first]) + x2[first], 0.0)

    def body(i, carry):
        centers, min_d2, key = carry
        key, sub = jax.random.split(key)
        # Gumbel-top-t draw of candidates ∝ weight * min_d2 (weighted D^2).
        logw = jnp.where(
            (mask > 0) & (min_d2 > 0), jnp.log(mask * min_d2), neg_inf
        )
        g = jax.random.gumbel(sub, (n,), dtype=x.dtype)
        _, cand = jax.lax.top_k(logw + g, t)
        # all-zero residual (duplicate data): fall back to the first row
        degenerate = jnp.logical_not(jnp.isfinite(jnp.max(logw)))
        cand = jnp.where(degenerate, first, cand)
        # Evaluate each candidate: potential = sum_j min(min_d2, d2(x_j, c)).
        xc = x[cand]                                            # (t, d)
        d2c = jnp.maximum(
            x2[None, :] - 2.0 * dot(xc, x.T)
            + jnp.sum(xc * xc, axis=1)[:, None],
            0.0,
        )                                                       # (t, n)
        pot = jnp.sum(jnp.minimum(min_d2[None, :], d2c) * mask[None, :], axis=1)
        best = jnp.argmin(pot)
        idx = cand[best]
        new_min_d2 = jnp.minimum(min_d2, d2c[best])
        return centers.at[i].set(x[idx]), new_min_d2, key

    centers, _, _ = jax.lax.fori_loop(1, k, body, (centers, min_d2, key_loop))
    return centers


@partial(jax.jit, static_argnames=("k", "assume_unmasked"))
def random_init(x: jax.Array, mask: jax.Array, key: jax.Array, k: int,
                assume_unmasked: bool = False) -> jax.Array:
    """Random seeding: k distinct unmasked rows via Gumbel scores.

    ``assume_unmasked=True`` (caller guarantees every row is real —
    no mesh padding, no weightCol) swaps the exact top-k for the
    hardware ``approx_max_k``: the scores are iid noise, so which of
    them surface is a uniform random distinct sample either way, and
    the approximate reduction skips the full sort network (measured
    ~100 ms of pure seeding tax at 20M rows; exact on CPU). With a
    REAL mask the exact top-k is required — the approximate per-tile
    reduction could let -inf (masked) scores survive when valid rows
    are few or concentrated."""
    n = x.shape[0]
    g = jax.random.gumbel(key, (n,), dtype=x.dtype)
    if assume_unmasked:
        _, idx = jax.lax.approx_max_k(g, k)
    else:
        scores = jnp.where(mask > 0, g, -jnp.inf)
        _, idx = jax.lax.top_k(scores, k)
    return x[idx]


def normalize_rows(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Unit-normalize rows — cosine distance == euclidean on normalized data."""
    norms = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
    return x / jnp.maximum(norms, eps)
