"""Core GEMM ops — the XLA equivalents of the reference's cuBLAS calls.

Reference surface (RAPIDSML.scala:40-71 -> JniRAPIDSML.java:64-69 ->
rapidsml_jni.cu):
  - ``dgemm``   C = BᵀB       (rapidsml_jni.cu:159-222, cublasDgemm OP_N/OP_T)
  - ``dgemm_b`` C = AᵀB       (rapidsml_jni.cu:224-300) — batch projection
  - ``dspr``    packed rank-1 (rapidsml_jni.cu:94-157, cublasDspr; dead on the
                 reference's main path, see SURVEY.md §3.2 — implemented here
                 for surface parity AND used by the native CPU fallback)
  - ``triuToFull`` packed-upper -> full symmetric (RapidsRowMatrix.scala:265-287)

TPU numerics: the MXU natively multiplies bf16 with fp32 accumulation.
``precision=HIGHEST`` runs the multi-pass bf16 decomposition giving ~fp32
product precision. fp64 (the reference's ``double[]`` surface) has no TPU
hardware path: under ``jax_enable_x64`` on CPU these ops run in true fp64
(the test oracle's numerics bar); on TPU, inputs compute in fp32-HIGHEST.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _dot_precision(precision: str):
    return {
        "default": jax.lax.Precision.DEFAULT,
        "high": jax.lax.Precision.HIGH,
        "highest": jax.lax.Precision.HIGHEST,
    }[precision]


PRECISIONS = (
    "auto", "default", "high", "highest", "dd",
    # named policy modes (ops/precision.py): f32 == highest bit-for-bit,
    # bf16x3 = 3-pass compensated split, bf16 = 1-pass serving-grade.
    "f32", "bf16x3", "bf16",
)


def validate_precision(value: str) -> str:
    """Shared setter-side validation for the user-facing precision params."""
    if value not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {'/'.join(PRECISIONS)}, got {value!r}"
        )
    return value


def resolve_precision(
    requested: str, input_dtype=None, x64_enabled=None, platform=None
) -> str:
    """Resolve a user-facing precision request to a concrete mode.

    ``"auto"`` picks ``"dd"`` (double-float fp64 emulation,
    ops.doubledouble) when the input carries fp64 data AND the compute
    platform is an ACCELERATOR with x64 off — the real-TPU case, where
    no native fp64 exists and emulation is the only route to the
    reference's all-``double[]`` numerics (JniRAPIDSML.java:64-69). On
    CPU the hardware does fp64 natively, so auto resolves "highest" and
    the right fix for fp64 semantics is enabling x64, not paying 4-5x
    for emulation. Explicit requests pass through unchanged.
    """
    if requested not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {'/'.join(PRECISIONS)}, got {requested!r}"
        )
    if requested != "auto":
        return requested
    if x64_enabled is None:
        x64_enabled = bool(jax.config.jax_enable_x64)
    if platform is None:
        platform = jax.default_backend()
    wants_f64 = input_dtype is not None and np.dtype(input_dtype) == np.float64
    return (
        "dd" if (wants_f64 and not x64_enabled and platform != "cpu") else "highest"
    )


@partial(jax.jit, static_argnames=("precision",))
def gemm_syrk(b: jax.Array, precision: str = "highest") -> jax.Array:
    """C = BᵀB for row-major B (rows, cols) -> (cols, cols).

    Replaces JNI ``dgemm`` (rapidsml_jni.cu:190-197): the reference feeds
    row-major B as column-major A=Bᵀ into cublasDgemm(OP_N, OP_T). Here it is
    a single dot_general that XLA tiles directly onto the MXU.
    """
    from spark_rapids_ml_tpu.ops.precision import make_dot

    return make_dot(precision)(b.T, b)


@partial(jax.jit, static_argnames=("precision",))
def project_rows(x: jax.Array, pc: jax.Array, precision: str = "highest") -> jax.Array:
    """C = X·pc — the device-resident row projection (the jitted twin of
    :func:`gemm_project` for inputs already laid out (n, d); transposing a
    concrete device array outside jit would materialize a copy, so this
    takes X directly). Same kernel the reference's disabled batch
    transform wanted (``dgemm_b``, rapidsml_jni.cu:269-276)."""
    from spark_rapids_ml_tpu.ops.precision import make_dot

    return make_dot(precision)(x, pc)


@partial(jax.jit, static_argnames=("precision",))
def gemm_project(a: jax.Array, b: jax.Array, precision: str = "highest") -> jax.Array:
    """C = AᵀB — the batched projection kernel.

    Replaces JNI ``dgemm_b`` (rapidsml_jni.cu:269-276). In the reference the
    consumer (GPU batch transform) is disabled as too slow
    (RapidsPCA.scala:172-185); here it is the live transform path.
    """
    from spark_rapids_ml_tpu.ops.precision import make_dot

    return make_dot(precision)(a.T, b)


@jax.jit
def spr(x: jax.Array, packed: jax.Array) -> jax.Array:
    """Packed upper-triangular (column-major, BLAS 'U') rank-1 update.

    A_packed += x xᵀ, only the upper triangle stored: element (i, j), i <= j,
    lives at ``j*(j+1)/2 + i`` — the same layout as cublasDspr FILL_MODE_UPPER
    (rapidsml_jni.cu:133-136) and Spark's BLAS.spr, so the treeAggregate path
    (RapidsRowMatrix.scala:208-233) is reproducible bit-for-layout.
    """
    n = x.shape[0]
    outer = jnp.outer(x, x)
    iu = _triu_indices_packed(n)
    return packed + outer[iu[0], iu[1]]


def _triu_indices_packed(n: int):
    """(row, col) indices ordered by the packed-upper column-major layout."""
    cols = np.concatenate([np.full(j + 1, j) for j in range(n)])
    rows = np.concatenate([np.arange(j + 1) for j in range(n)])
    return rows, cols


@jax.jit
def triu_to_full(packed: jax.Array) -> jax.Array:
    """Packed upper-triangular -> full symmetric matrix.

    Replaces ``RapidsRowMatrix.triuToFull`` (RapidsRowMatrix.scala:265-287).
    n is recovered from nt = n(n+1)/2.
    """
    nt = packed.shape[0]
    n = int((np.sqrt(8 * nt + 1) - 1) / 2)
    if n * (n + 1) // 2 != nt:
        raise ValueError(f"packed length {nt} is not triangular")
    rows, cols = _triu_indices_packed(n)
    full = jnp.zeros((n, n), dtype=packed.dtype)
    full = full.at[rows, cols].set(packed)
    off_diag = jnp.where(jnp.arange(n)[:, None] < jnp.arange(n)[None, :], full, 0.0)
    return full + off_diag.T


def soft_threshold(v, t):
    """Proximal operator of t*||.||_1: sign(v) * max(|v| - t, 0)."""
    return jnp.sign(v) * jnp.maximum(jnp.abs(v) - t, 0.0)
