"""Logistic regression kernels — masked softmax/sigmoid loss + jitted L-BFGS.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md §2);
the model surface mirrors ``org.apache.spark.ml.classification
.LogisticRegression``, whose optimizer is breeze L-BFGS over a
DiffFunction aggregated with treeAggregate. Here the entire optimization is
ONE jitted program: loss+gradient are masked GEMMs on the MXU and the L-BFGS
update (optax.lbfgs with zoom linesearch) runs inside ``lax.while_loop`` —
no per-iteration host round-trip. Under a mesh, ``x``/``y``/``mask`` arrive
row-sharded and XLA inserts the gradient psum over ICI (GSPMD), giving the
treeAggregate analogue for free.

Objective (Spark semantics):
    (1/n) sum_i logloss_i
      + regParam * (alpha ||w||_1 + (1 - alpha)/2 ||w||^2)
with the penalty on coefficients of STANDARDIZED features when
``standardization=True`` (optimize in scaled space, map back), intercept
never penalized. alpha = 0 (pure L2) runs jitted L-BFGS
(:func:`fit_logistic`); alpha > 0 runs FISTA proximal gradient
(:func:`fit_logistic_elastic_net`) — Spark's OWL-QN analogue. Multinomial
uses the over-parameterized softmax; when regParam == 0 the class axis is
mean-centered for identifiability (Spark does the same pivoting
correction).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from spark_rapids_ml_tpu.ops.linalg import soft_threshold
from spark_rapids_ml_tpu.ops.precision import as_dot, make_dot


class LogisticFit(NamedTuple):
    """Result of :func:`fit_logistic` (all device arrays)."""

    weights: jax.Array  # (d, c) coefficients in ORIGINAL feature space
    intercepts: jax.Array  # (c,)
    n_iter: jax.Array  # scalar int
    loss: jax.Array  # final objective value (standardized space)


#: Row-block length of the fused one-pass objective: big enough that the
#: per-evaluation GEMMs stay MXU-bound, small enough that a block's
#: standardized slice is a cache/VMEM-resident temporary instead of a
#: materialized (n, d) HBM array.
_FUSED_BLOCK_ROWS = 65536


def _make_logistic_loss(
    x, y_target, mask, offset, scale, n, reg_param, c, fit_intercept, dot,
    fused=False,
):
    """The ONE home of the (standardized-space) logistic objective —
    closed over by the monolithic :func:`fit_logistic` program, the
    segmented :func:`_lbfgs_segment` program, and the finalizer, so all
    three optimize/evaluate literally the same expression (the
    bit-identity bar of the checkpoint subsystem).

    ``fused=False`` returns the plain objective (gradients via autodiff,
    which saves the standardized (n, d) design as a residual — X is
    effectively streamed twice per evaluation). ``fused=True`` returns a
    ``jax.custom_vjp`` objective whose forward pass computes the value
    AND the analytic gradient in ONE blocked sweep over X — the algebra
    needs only X^T(p - y) and the logloss sum, so each row block's
    standardized slice lives and dies on-chip (VERDICT r5 #4: the second
    X pass was ~16.7% of the fit's HBM traffic). The fused callable also
    exposes ``.value_and_grad(params)`` for drivers that want both
    without round-tripping through AD. Fused and legacy agree to float
    tolerance (per-block partial sums reduce in a different order);
    every segmented/monolithic pair shares ONE flag, so checkpoint
    bit-identity is preserved in both modes."""
    dot = as_dot(dot)

    def _block_terms(xb, yb, mb, w, b):
        """One row block's (masked loss sum, unnormalized dL/dw, dL/db)."""
        xs = (xb - offset) / scale
        logits = dot(xs, w)
        if fit_intercept:
            logits = logits + b
        if c == 1:
            z = logits[:, 0]
            # log(1+e^z) - y z, numerically stable via softplus
            per_row = jax.nn.softplus(z) - yb * z
            dz = ((jax.nn.sigmoid(z) - yb) * mb)[:, None]
        else:
            logp = jax.nn.log_softmax(logits, axis=1)
            per_row = -jnp.sum(yb * logp, axis=1)
            dz = (jnp.exp(logp) - yb) * mb[:, None]
        loss_b = jnp.sum(per_row * mb)
        gw_b = dot(xs.T, dz)
        gb_b = jnp.sum(dz, axis=0)
        return loss_b, gw_b, gb_b

    if not fused:

        def loss_fn(params):
            w, b = params
            xs = (x - offset) / scale
            logits = dot(xs, w)
            if fit_intercept:
                logits = logits + b
            if c == 1:
                z = logits[:, 0]
                # log(1+e^z) - y z, numerically stable via softplus
                per_row = jax.nn.softplus(z) - y_target * z
            else:
                per_row = -jnp.sum(
                    y_target * jax.nn.log_softmax(logits, axis=1), axis=1
                )
            data_loss = jnp.sum(per_row * mask) / n
            return data_loss + 0.5 * reg_param * jnp.sum(w * w)

        return loss_fn

    nrows = x.shape[0]
    bs = min(_FUSED_BLOCK_ROWS, nrows)

    def value_and_grad(params):
        w, b = params
        if nrows <= bs:
            loss_s, gw_s, gb_s = _block_terms(x, y_target, mask, w, b)
        else:
            nb = -(-nrows // bs)

            def body(i, acc):
                l_a, gw_a, gb_a = acc
                # The last block slides back to stay in bounds; rows the
                # previous block already counted mask to zero.
                start = jnp.minimum(i * bs, nrows - bs)
                xb = jax.lax.dynamic_slice_in_dim(x, start, bs)
                yb = jax.lax.dynamic_slice_in_dim(y_target, start, bs)
                mb = jax.lax.dynamic_slice_in_dim(mask, start, bs)
                keep = (start + jnp.arange(bs)) >= i * bs
                l_b, gw_b, gb_b = _block_terms(
                    xb, yb, mb * keep.astype(mb.dtype), w, b
                )
                return l_a + l_b, gw_a + gw_b, gb_a + gb_b

            loss_s, gw_s, gb_s = jax.lax.fori_loop(
                0, nb, body,
                (jnp.zeros((), x.dtype), jnp.zeros_like(w), jnp.zeros((c,), x.dtype)),
            )
        value = loss_s / n + 0.5 * reg_param * jnp.sum(w * w)
        gw = gw_s / n + reg_param * w
        gb = gb_s / n if fit_intercept else jnp.zeros_like(b)
        return value, (gw, gb.astype(b.dtype))

    @jax.custom_vjp
    def loss_fn(params):
        return value_and_grad(params)[0]

    def _fwd(params):
        value, grad = value_and_grad(params)
        return value, grad

    def _bwd(grad, ct):
        return (jax.tree_util.tree_map(lambda g: g * ct, grad),)

    loss_fn.defvjp(_fwd, _bwd)
    loss_fn.value_and_grad = value_and_grad
    return loss_fn


def _masked_feature_moments(x: jax.Array, mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Weighted per-feature mean and stddev (population, like Spark's scaler).

    The mask may carry fractional weightCol weights, so it must enter the
    variance LINEARLY — squaring it (masking the residual instead of the
    squared residual) would inflate sigma by sqrt(w) under uniform weights.
    """
    n = jnp.sum(mask)
    mean = jnp.sum(x * mask[:, None], axis=0) / n
    var = jnp.sum(((x - mean) ** 2) * mask[:, None], axis=0) / n
    return mean, jnp.sqrt(var)


@partial(
    jax.jit,
    static_argnames=(
        "n_classes",
        "fit_intercept",
        "standardization",
        "max_iter",
        "precision",
        "multinomial",
        "fused",
    ),
)
def fit_logistic(
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    n_classes: int,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    standardization: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    precision: str = "highest",
    multinomial: bool = False,
    init_w: jax.Array | None = None,
    init_b: jax.Array | None = None,
    fused: bool = True,
) -> LogisticFit:
    """Fit binomial or multinomial logistic regression.

    ``init_w`` (d, c) / ``init_b`` (c,) warm-start the optimizer from an
    ORIGINAL-space solution (e.g. a previous model) — mapped into the
    standardized optimization space internally; default zeros.

    ``x``: (n, d); ``y``: (n,) integer labels in [0, n_classes); ``mask``:
    (n,) 1.0 for real rows, 0.0 for padding (mesh row-sharding pads).
    Binomial (``n_classes == 2`` and not ``multinomial``) trains a single
    sigmoid column (c = 1); ``multinomial=True`` trains the full
    (d, n_classes) softmax matrix even at 2 classes — the two families'
    optima differ under L2 (softmax splits the penalty across both class
    columns), so the 2-class case must NOT be collapsed to sigmoid when
    multinomial semantics are requested.
    """
    if n_classes < 2:
        raise ValueError(f"need at least 2 classes, got {n_classes}")
    c = n_classes if (multinomial or n_classes > 2) else 1
    d = x.shape[1]
    dtype = x.dtype
    # Older optax cannot trace its zoom linesearch with f32 params when
    # x64 is on (weak-f64 literals leak into the f32 linesearch state —
    # utils/compat.optax_lbfgs_f32_works probes it). Solve in f64 there
    # and cast the fitted params back: numerics only improve, device
    # residence is unchanged.
    out_dtype = None
    if dtype == jnp.float32 and jax.config.jax_enable_x64:
        from spark_rapids_ml_tpu.utils.compat import optax_lbfgs_f32_works

        if not optax_lbfgs_f32_works():
            out_dtype = dtype
            dtype = jnp.float64
            x = x.astype(dtype)
            mask = mask.astype(dtype)
    dot = make_dot(precision)
    n = jnp.sum(mask)

    mean, sigma = _masked_feature_moments(x, mask)
    # Padded / constant features have sigma 0 — scale by 1 there (their
    # coefficients stay 0: zero column => zero gradient under L2 from init 0).
    safe_sigma = jnp.where(sigma > 0, sigma, 1.0)
    if standardization:
        # Center ONLY when an intercept exists to absorb the shift back in
        # original space; without an intercept, scale-only (Spark does the
        # same — otherwise the returned coefficients would describe a
        # different function than the one optimized).
        offset = mean if fit_intercept else jnp.zeros_like(mean)
        scale = safe_sigma
    else:
        offset = jnp.zeros_like(mean)
        scale = jnp.ones_like(safe_sigma)

    if c == 1:
        y_target = (y == 1).astype(dtype)
    else:
        y_target = jax.nn.one_hot(y, c, dtype=dtype)

    loss_fn = _make_logistic_loss(
        x, y_target, mask, offset, scale, n, reg_param, c, fit_intercept, dot,
        fused=fused,
    )

    if init_w is None:
        w0 = jnp.zeros((d, c), dtype=dtype)
        b0 = jnp.zeros((c,), dtype=dtype)
    else:
        # Inverse of the final back-map: the optimizer works in
        # standardized space (w_std = w_orig * scale; the intercept
        # re-absorbs the centering offset).
        w_orig0 = jnp.asarray(init_w, dtype=dtype)
        w0 = w_orig0 * scale[:, None]
        if fit_intercept:
            # Absorb the centering offset whether or not an original-space
            # intercept was supplied — (w_orig, 0) must start as the SAME
            # decision function, not a shifted one.
            b_orig0 = (
                jnp.asarray(init_b, dtype=dtype)
                if init_b is not None
                else jnp.zeros((c,), dtype=dtype)
            )
            b0 = b_orig0 + dot(offset, w_orig0)
        else:
            # No intercept in the model: b is never optimized (zero
            # gradient), so a stale nonzero init would leak into predict.
            b0 = jnp.zeros((c,), dtype=dtype)
    params0 = (w0, b0)

    solver = optax.lbfgs()
    from spark_rapids_ml_tpu.utils.compat import value_and_grad_from_state

    value_and_grad = value_and_grad_from_state(loss_fn)
    state0 = solver.init(params0)

    def cond(carry):
        _params, _state, it, gnorm = carry
        return jnp.logical_and(it < max_iter, gnorm > tol)

    def body(carry):
        params, state, it, _ = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = solver.update(
            grad, state, params, value=value, grad=grad, value_fn=loss_fn
        )
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grad)
        return params, state, it + 1, gnorm

    init = (params0, state0, jnp.asarray(0), jnp.asarray(jnp.inf, dtype=dtype))
    (w, b), state, n_iter, _ = jax.lax.while_loop(cond, body, init)

    # Identifiability pivot for unregularized softmax (Spark's centering).
    if c > 1:
        do_center = reg_param == 0.0
        w = jnp.where(do_center, w - jnp.mean(w, axis=1, keepdims=True), w)
        b = jnp.where(do_center, b - jnp.mean(b), b)

    # Map standardized-space solution back to original feature space.
    w_orig = w / scale[:, None]
    b_orig = b - dot(offset, w_orig) if fit_intercept else b
    final_loss = loss_fn((w, b))
    if out_dtype is not None:  # f64 fallback solve: hand back f32
        w_orig = w_orig.astype(out_dtype)
        b_orig = b_orig.astype(out_dtype)
        final_loss = final_loss.astype(out_dtype)
    return LogisticFit(w_orig, b_orig, n_iter, final_loss)


@partial(jax.jit, static_argnames=("fit_intercept", "standardization"))
def _logistic_prep(x, mask, fit_intercept: bool, standardization: bool):
    """The standardizer inputs of :func:`fit_logistic` — (offset, scale,
    n) as one small program, shared by every segment of a resumable fit
    instead of being refolded into each one."""
    n = jnp.sum(mask)
    mean, sigma = _masked_feature_moments(x, mask)
    safe_sigma = jnp.where(sigma > 0, sigma, 1.0)
    if standardization:
        offset = mean if fit_intercept else jnp.zeros_like(mean)
        scale = safe_sigma
    else:
        offset = jnp.zeros_like(mean)
        scale = jnp.ones_like(safe_sigma)
    return offset, scale, n


@partial(
    jax.jit,
    static_argnames=(
        "c", "fit_intercept", "max_iter", "every", "precision", "fused",
    ),
)
def _lbfgs_segment(
    x, y_target, mask, offset, scale, n, reg_param, tol,
    params, opt_state, it, gnorm,
    c: int, fit_intercept: bool, max_iter: int, every: int, precision: str,
    fused: bool = True,
):
    """Up to ``every`` L-BFGS iterations from an explicit optimizer
    state — exactly :func:`fit_logistic`'s loop body and stopping rule
    plus a segment budget, with the full (params, optax state, iteration,
    gradient norm) carry visible as a pytree between segments."""
    dot = make_dot(precision)
    loss_fn = _make_logistic_loss(
        x, y_target, mask, offset, scale, n, reg_param, c, fit_intercept, dot,
        fused=fused,
    )
    solver = optax.lbfgs()
    from spark_rapids_ml_tpu.utils.compat import value_and_grad_from_state

    value_and_grad = value_and_grad_from_state(loss_fn)

    def cond(carry):
        _params, _state, it, gnorm, seg = carry
        return jnp.logical_and(
            jnp.logical_and(it < max_iter, gnorm > tol), seg < every
        )

    def body(carry):
        params, state, it, _, seg = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = solver.update(
            grad, state, params, value=value, grad=grad, value_fn=loss_fn
        )
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grad)
        return params, state, it + 1, gnorm, seg + 1

    params, opt_state, it, gnorm, _ = jax.lax.while_loop(
        cond, body, (params, opt_state, it, gnorm, 0)
    )
    return params, opt_state, it, gnorm


@partial(
    jax.jit, static_argnames=("c", "fit_intercept", "precision", "fused")
)
def _logistic_finalize(
    x, y_target, mask, offset, scale, n, reg_param, w, b,
    c: int, fit_intercept: bool, precision: str, fused: bool = True,
):
    """:func:`fit_logistic`'s post-solve tail (identifiability pivot,
    back-map to original feature space, final objective) as its own
    program for the segmented driver."""
    dot = make_dot(precision)
    loss_fn = _make_logistic_loss(
        x, y_target, mask, offset, scale, n, reg_param, c, fit_intercept, dot,
        fused=fused,
    )
    if c > 1:
        do_center = reg_param == 0.0
        w = jnp.where(do_center, w - jnp.mean(w, axis=1, keepdims=True), w)
        b = jnp.where(do_center, b - jnp.mean(b), b)
    w_orig = w / scale[:, None]
    b_orig = b - dot(offset, w_orig) if fit_intercept else b
    return w_orig, b_orig, loss_fn((w, b))


def fit_logistic_resumable(
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    checkpointer,
    n_classes: int,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    standardization: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    precision: str = "highest",
    multinomial: bool = False,
    init_w: jax.Array | None = None,
    init_b: jax.Array | None = None,
    mesh=None,
    fused: bool = True,
) -> LogisticFit:
    """Preemption-tolerant :func:`fit_logistic` (the L-BFGS / L2 path):
    a host outer loop over jitted L-BFGS segments, the (params, optimizer
    state, iteration counter, gradient norm) pytree snapshotted
    asynchronously between segments, the fit resumed mid-solve from the
    latest valid checkpoint. Same returns, bit-identical solution."""
    from spark_rapids_ml_tpu.robustness.checkpoint import (
        replicate_state_onto_mesh,
        segment_boundary,
    )
    import time

    from spark_rapids_ml_tpu.observability.costs import ledgered_call
    from spark_rapids_ml_tpu.observability.metrics import observe_segment_seconds
    from spark_rapids_ml_tpu.robustness.faults import fault_point
    from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange, bump_counter

    if n_classes < 2:
        raise ValueError(f"need at least 2 classes, got {n_classes}")
    c = n_classes if (multinomial or n_classes > 2) else 1
    d = x.shape[1]
    dtype = x.dtype
    out_dtype = None
    if dtype == jnp.float32 and jax.config.jax_enable_x64:
        from spark_rapids_ml_tpu.utils.compat import optax_lbfgs_f32_works

        if not optax_lbfgs_f32_works():
            out_dtype = dtype
            dtype = jnp.float64
            x = x.astype(dtype)
            mask = mask.astype(dtype)
    dot = make_dot(precision)
    offset, scale, n = _logistic_prep(
        x, mask, fit_intercept=fit_intercept, standardization=standardization
    )

    if c == 1:
        y_target = (y == 1).astype(dtype)
    else:
        y_target = jax.nn.one_hot(y, c, dtype=dtype)

    if init_w is None:
        w0 = jnp.zeros((d, c), dtype=dtype)
        b0 = jnp.zeros((c,), dtype=dtype)
    else:
        w_orig0 = jnp.asarray(init_w, dtype=dtype)
        w0 = w_orig0 * scale[:, None]
        if fit_intercept:
            b_orig0 = (
                jnp.asarray(init_b, dtype=dtype)
                if init_b is not None
                else jnp.zeros((c,), dtype=dtype)
            )
            b0 = b_orig0 + dot(offset, w_orig0)
        else:
            b0 = jnp.zeros((c,), dtype=dtype)

    params0 = (w0, b0)
    state0 = optax.lbfgs().init(params0)
    carry = (params0, state0, jnp.asarray(0), jnp.asarray(jnp.inf, dtype=dtype))
    restored = checkpointer.restore_latest(template=carry)
    if restored is not None:
        _, carry = restored
        if mesh is not None:
            carry = replicate_state_onto_mesh(carry, mesh)

    while True:
        it, gn = int(carry[2]), float(carry[3])
        if not (it < max_iter and gn > tol):
            break
        seg_t0 = time.perf_counter()
        with TraceRange("segment logistic.lbfgs", TraceColor.PURPLE):
            fault_point("solver.segment")
            params, opt_state, it_a, gn_a = ledgered_call(
                _lbfgs_segment,
                (x, y_target, mask, offset, scale, n,
                 reg_param, tol, carry[0], carry[1], carry[2], carry[3]),
                static=dict(
                    c=c, fit_intercept=fit_intercept, max_iter=max_iter,
                    every=checkpointer.every, precision=precision,
                    fused=fused,
                ),
                name="logistic.lbfgs.segment",
            )
            carry = (params, opt_state, it_a, gn_a)
            bump_counter("checkpoint.segments")
            bump_counter("checkpoint.solver_iters", int(it_a) - it)
        observe_segment_seconds("logistic.lbfgs", time.perf_counter() - seg_t0)
        checkpointer.save_async(int(it_a), carry)
        segment_boundary(checkpointer)

    (w, b), _, n_iter, _ = carry
    w_orig, b_orig, final_loss = _logistic_finalize(
        x, y_target, mask, offset, scale, n, reg_param, w, b,
        c=c, fit_intercept=fit_intercept, precision=precision, fused=fused,
    )
    if out_dtype is not None:  # f64 fallback solve: hand back f32
        w_orig = w_orig.astype(out_dtype)
        b_orig = b_orig.astype(out_dtype)
        final_loss = final_loss.astype(out_dtype)
    checkpointer.finalize_success()
    return LogisticFit(w_orig, b_orig, n_iter, final_loss)


@partial(
    jax.jit,
    static_argnames=(
        "n_classes",
        "fit_intercept",
        "standardization",
        "max_iter",
        "precision",
        "multinomial",
        "fused",
    ),
)
def fit_logistic_elastic_net(
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    n_classes: int,
    reg_param: float,
    elastic_net_param: float,
    fit_intercept: bool = True,
    standardization: bool = True,
    max_iter: int = 500,
    tol: float = 1e-7,
    precision: str = "highest",
    multinomial: bool = False,
    fused: bool = True,
) -> LogisticFit:
    """Elastic-net logistic regression by FISTA (proximal gradient).

    Spark routes elasticNetParam > 0 to breeze OWL-QN; the TPU formulation
    is accelerated proximal gradient: the smooth part (log-loss + L2) takes
    one gradient GEMM pair per iteration, the L1 part is a soft-threshold
    prox on the coefficients (intercept never penalized), and the step is
    1/L with L from a power-iteration bound on the standardized Gram
    spectral norm — everything inside one ``lax.while_loop``.
    """
    if n_classes < 2:
        raise ValueError(f"need at least 2 classes, got {n_classes}")
    c = n_classes if (multinomial or n_classes > 2) else 1
    d = x.shape[1]
    dtype = x.dtype
    dot = make_dot(precision)
    n = jnp.sum(mask)

    mean, sigma = _masked_feature_moments(x, mask)
    safe_sigma = jnp.where(sigma > 0, sigma, 1.0)
    if standardization:
        offset = mean if fit_intercept else jnp.zeros_like(mean)
        scale = safe_sigma
    else:
        offset = jnp.zeros_like(mean)
        scale = jnp.ones_like(safe_sigma)

    if c == 1:
        y_target = (y == 1).astype(dtype)
    else:
        y_target = jax.nn.one_hot(y, c, dtype=dtype)

    reg1 = reg_param * elastic_net_param
    reg2 = reg_param * (1.0 - elastic_net_param)

    def xs_matvec(v):
        return dot((x - offset) / scale, v)

    def xs_rmatvec(u):
        return dot(((x - offset) / scale).T, u * mask)

    # Spectral norm of the masked standardized design via power iteration:
    # L_data = lambda_max(Xs^T M Xs) * curvature_bound / n, where the
    # per-row logistic curvature is <= 1/4 (sigmoid) or <= 1/2 (softmax).
    def power_body(_, v):
        u = xs_rmatvec(xs_matvec(v))
        return u / jnp.maximum(jnp.linalg.norm(u), 1e-30)

    # Randomized (fixed-key) start: a deterministic uniform vector can be
    # exactly orthogonal to the dominant eigenvector of a structured Gram
    # (e.g. d=2 with negative correlation), which would underestimate
    # lambda_max and make the fixed FISTA step divergent.
    v0 = jax.random.normal(jax.random.key(0), (d,), dtype=dtype)
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)
    v = jax.lax.fori_loop(0, 30, power_body, v0)
    lam_max = jnp.linalg.norm(xs_rmatvec(xs_matvec(v)))
    curvature = 0.25 if c == 1 else 0.5
    # 1.1 safety margin: power iteration converges from below.
    lip = 1.1 * lam_max * curvature / n + reg2 + 1e-12

    # The FISTA smooth part (log-loss + L2 at reg2) IS the L-BFGS
    # objective at reg_param=reg2 — so the fused one-pass builder serves
    # both solvers from the same algebra.
    if fused:
        smooth_loss = _make_logistic_loss(
            x, y_target, mask, offset, scale, n, reg2, c, fit_intercept,
            dot, fused=True,
        )

        def grad_fn(params):
            return smooth_loss.value_and_grad(params)[1]

    else:

        def smooth_loss(params):
            w, b = params
            logits = xs_matvec(w)
            if fit_intercept:
                logits = logits + b
            if c == 1:
                z = logits[:, 0]
                per_row = jax.nn.softplus(z) - y_target * z
            else:
                per_row = -jnp.sum(
                    y_target * jax.nn.log_softmax(logits, axis=1), axis=1
                )
            return jnp.sum(per_row * mask) / n + 0.5 * reg2 * jnp.sum(w * w)

        grad_fn = jax.grad(smooth_loss)

    w0 = jnp.zeros((d, c), dtype=dtype)
    b0 = jnp.zeros((c,), dtype=dtype)

    def cond(carry):
        _, _, _, _, _, it, delta = carry
        return jnp.logical_and(it < max_iter, delta > tol)

    def body(carry):
        w, b, zw, zb, t, it, _ = carry
        gw, gb = grad_fn((zw, zb))
        w_new = soft_threshold(zw - gw / lip, reg1 / lip)
        b_new = jnp.where(fit_intercept, zb - gb / lip, zb)
        t_new = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
        mom = (t - 1.0) / t_new
        zw_new = w_new + mom * (w_new - w)
        zb_new = b_new + mom * (b_new - b)
        delta = jnp.maximum(
            jnp.max(jnp.abs(w_new - w)), jnp.max(jnp.abs(b_new - b))
        )
        return w_new, b_new, zw_new, zb_new, t_new, it + 1, delta

    init = (
        w0, b0, w0, b0,
        jnp.asarray(1.0, dtype), jnp.asarray(0), jnp.asarray(jnp.inf, dtype),
    )
    w, b, _, _, _, n_iter, _ = jax.lax.while_loop(cond, body, init)

    w_orig = w / scale[:, None]
    b_orig = b - dot(offset, w_orig) if fit_intercept else b
    final_loss = smooth_loss((w, b)) + reg1 * jnp.sum(jnp.abs(w))
    return LogisticFit(w_orig, b_orig, n_iter, final_loss)


@partial(
    jax.jit, static_argnames=("c", "fit_intercept", "precision", "fused")
)
def _stream_block_value_grad(
    xb, yb, w, b, offset, scale, c, fit_intercept, precision,
    fused: bool = True,
):
    """UNnormalized block loss + gradient contribution for the streaming
    fit: sum_i logloss_i over this block only (the driver divides by the
    global n and adds the L2 term once). ``fused=True`` computes the
    value and the analytic gradient in one sweep of the block (no AD
    residual); ``fused=False`` keeps the autodiff formulation."""
    dot = make_dot(precision)
    dtype = xb.dtype
    if c == 1:
        y_t = (yb == 1).astype(dtype)
    else:
        y_t = jax.nn.one_hot(yb, c, dtype=dtype)

    if fused:
        xs = (xb - offset) / scale
        logits = dot(xs, w)
        if fit_intercept:
            logits = logits + b
        if c == 1:
            z = logits[:, 0]
            per_row = jax.nn.softplus(z) - y_t * z
            dz = (jax.nn.sigmoid(z) - y_t)[:, None]
        else:
            logp = jax.nn.log_softmax(logits, axis=1)
            per_row = -jnp.sum(y_t * logp, axis=1)
            dz = jnp.exp(logp) - y_t
        val = jnp.sum(per_row)
        gw = dot(xs.T, dz)
        gb = jnp.sum(dz, axis=0) if fit_intercept else jnp.zeros_like(b)
        return val, gw, gb

    def f(params):
        w_, b_ = params
        xs = (xb - offset) / scale
        logits = dot(xs, w_)
        if fit_intercept:
            logits = logits + b_
        if c == 1:
            z = logits[:, 0]
            per_row = jax.nn.softplus(z) - y_t * z
        else:
            per_row = -jnp.sum(y_t * jax.nn.log_softmax(logits, axis=1), axis=1)
        return jnp.sum(per_row)

    val, (gw, gb) = jax.value_and_grad(f)((w, b))
    return val, gw, gb


def streaming_label_feature_stats(pairs):
    """One pass over (X_block, y_block) pairs: feature moments in host
    fp64 (n, mean, sigma — the standardizer inputs) plus label integrality
    and range for the class count. O(d) state."""
    n = 0
    s = ss = None
    y_max = -1
    y_int_ok = True
    for xb, yb in pairs:
        b = np.asarray(xb, dtype=np.float64)
        yv = np.asarray(yb).ravel()
        if s is None:
            s = np.zeros(b.shape[1])
            ss = np.zeros(b.shape[1])
        s += b.sum(axis=0)
        ss += (b * b).sum(axis=0)
        n += b.shape[0]
        if yv.size:
            yi = yv.astype(np.int64)
            if not np.array_equal(yi, yv) or yi.min() < 0:
                y_int_ok = False
            y_max = max(y_max, int(yi.max()))
    if n == 0:
        raise ValueError("streaming source yielded no rows")
    mean = s / n
    sigma = np.sqrt(np.maximum(ss / n - mean * mean, 0.0))
    return n, mean, sigma, y_max, y_int_ok


def fit_logistic_streaming(
    pairs_factory,
    n_classes: int,
    n: int,
    mean: np.ndarray,
    sigma: np.ndarray,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    standardization: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    precision: str = "highest",
    multinomial: bool = False,
    dtype=None,
    fused: bool = True,
) -> LogisticFit:
    """Multi-pass L-BFGS fit over a RE-ITERABLE (X_block, y_block) source.

    Same objective and standardization semantics as :func:`fit_logistic`;
    memory is O(block + d*c): each objective evaluation streams the blocks
    through :func:`_stream_block_value_grad` (device GEMMs, device
    accumulation) while scipy's L-BFGS-B drives the O(d*c) optimizer state
    on host — the optimizer round trip per data pass is exactly the shape
    Spark's breeze-over-treeAggregate loop has (one driver update per
    distributed pass), so the streaming fit is also the faithful analogue
    of the reference lineage's execution model. Feature moments arrive
    precomputed (:func:`streaming_label_feature_stats`) so the caller's
    label scan and the standardizer share one pass.
    """
    from scipy.optimize import minimize

    if n_classes < 2:
        raise ValueError(f"need at least 2 classes, got {n_classes}")
    c = n_classes if (multinomial or n_classes > 2) else 1
    d = mean.shape[0]
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    np_dtype = np.dtype(dtype)

    safe_sigma = np.where(sigma > 0, sigma, 1.0)
    if standardization:
        offset = mean if fit_intercept else np.zeros_like(mean)
        scale = safe_sigma
    else:
        offset = np.zeros_like(mean)
        scale = np.ones_like(safe_sigma)
    offset_j = jnp.asarray(offset, dtype=dtype)
    scale_j = jnp.asarray(scale, dtype=dtype)

    n_b = c if fit_intercept else 0

    def fun_grad(theta):
        from spark_rapids_ml_tpu.robustness.faults import fault_point

        fault_point("solver.segment")
        w = theta[: d * c].reshape(d, c)
        b = theta[d * c :] if fit_intercept else np.zeros(c)
        wj = jnp.asarray(w.astype(np_dtype))
        bj = jnp.asarray(b.astype(np_dtype))
        tot = jnp.zeros((), dtype)
        gw_acc = jnp.zeros((d, c), dtype)
        gb_acc = jnp.zeros((c,), dtype)

        def _upload(pair):
            xb, yb = pair
            return (
                jnp.asarray(np.ascontiguousarray(xb, dtype=np_dtype)),
                jnp.asarray(np.asarray(yb).ravel().astype(np.int32)),
            )

        from spark_rapids_ml_tpu.core.serving import prefetch_blocks

        # Double-buffered: pair k+1 densifies/uploads while pair k's
        # value+grad program runs; accumulation order is unchanged.
        for xj, yj in prefetch_blocks(pairs_factory(), _upload):
            v, gw, gb = _stream_block_value_grad(
                xj, yj, wj, bj, offset_j, scale_j, c, fit_intercept,
                precision, fused,
            )
            tot, gw_acc, gb_acc = tot + v, gw_acc + gw, gb_acc + gb
        val = float(tot) / n + 0.5 * reg_param * float(np.sum(w * w))
        g_w = np.asarray(gw_acc, dtype=np.float64) / n + reg_param * w
        out = [g_w.ravel()]
        if fit_intercept:
            out.append(np.asarray(gb_acc, dtype=np.float64) / n)
        return val, np.concatenate(out)

    theta0 = np.zeros(d * c + n_b)
    res = minimize(
        fun_grad,
        theta0,
        jac=True,
        method="L-BFGS-B",
        options={"maxiter": max_iter, "gtol": tol, "ftol": 1e-14},
    )
    w = res.x[: d * c].reshape(d, c)
    b = res.x[d * c :] if fit_intercept else np.zeros(c)

    if c > 1 and reg_param == 0.0:
        # Identifiability pivot for unregularized softmax (fit_logistic parity).
        w = w - w.mean(axis=1, keepdims=True)
        b = b - b.mean()

    w_orig = w / scale[:, None]
    b_orig = b - offset @ w_orig if fit_intercept else b
    return LogisticFit(
        w_orig, b_orig, np.int64(res.nit), np.float64(res.fun)
    )


@partial(jax.jit, static_argnames=("n_classes", "precision"))
def predict_logistic(
    x: jax.Array,
    weights: jax.Array,
    intercepts: jax.Array,
    n_classes: int,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(labels, probabilities (n, n_classes), raw logits (n, n_classes))."""
    dot = make_dot(precision)
    logits = dot(x, weights) + intercepts
    if weights.shape[1] == 1:
        z = logits[:, 0]
        p1 = jax.nn.sigmoid(z)
        probs = jnp.stack([1.0 - p1, p1], axis=1)
        raw = jnp.stack([-z, z], axis=1)
        labels = (p1 > 0.5).astype(jnp.int32)
    else:
        probs = jax.nn.softmax(logits, axis=1)
        raw = logits
        labels = jnp.argmax(logits, axis=1).astype(jnp.int32)
    return labels, probs, raw


@jax.jit
def classification_metrics(y: jax.Array, pred: jax.Array, mask: jax.Array):
    """(accuracy, error_rate) over unmasked rows."""
    n = jnp.sum(mask)
    correct = jnp.sum((y == pred).astype(mask.dtype) * mask)
    acc = correct / n
    return acc, 1.0 - acc
