"""Mixed-precision MXU policy layer — named GEMM modes for every hot path.

"Large Scale Distributed Linear Algebra With TPUs" (arXiv:2112.09017)
shows fp32-grade GEMM composed from bf16 MXU passes running near bf16
peak. The MXU natively multiplies bf16 with fp32 accumulation;
``lax.Precision.HIGHEST`` spends SIX bf16 passes per product for full
fp32 fidelity. This module names the useful points on that curve and
gives every GEMM-dominated op family ONE policy chokepoint:

  ``f32``     today's HIGHEST, bit-for-bit — the default everywhere.
  ``bf16x3``  the classic 3-pass compensated split: a = hi + lo with
              both parts bf16-representable, A·B ≈ Ahi·Bhi + Ahi·Blo
              + Alo·Bhi (only the lo·lo term is dropped). Documented
              bound: max rel err ≤ 2e-4 vs f32 (measured ~1e-6 on the
              benchmark shapes; the bound is the COMMIT bar, not the
              typical error). Half of HIGHEST's passes.
  ``bf16``    plain bf16 multiply, f32 accumulate — ONE pass, for
              tolerance-insensitive serving/predict paths only.
              Documented bound: max rel err ≤ 3e-2 vs f32.

The hi/lo parts are bf16-representable values carried in f32
containers, so single-pass dots on the parts are EXACT products on
both the MXU and CPU — the compensated result is backend-consistent,
which is what lets CPU CI pin the parity tables.

Policy resolution (:func:`resolve_policy`) layers, strongest first:
explicit ``setPrecision(...)`` on the estimator, the per-family
``TPUML_PRECISION_<FAMILY>`` knob, the global ``TPUML_PRECISION``
knob, a committed autotuner decision (knob ``precision_mode``), then
the family default — so with no knobs and ``TPUML_AUTOTUNE=off``
nothing changes, bit-for-bit.

The autotuner is the gatekeeper for automatic adoption
(:func:`tune_precision`): a candidate mode commits iff its measured
probe wall BEATS the f32 incumbent AND the parity probe holds at the
documented bound; a regression or parity miss is recorded ``rejected``
in the tune store and the incumbent stands.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.ops.linalg import _dot_precision

# Named policy modes (new vocabulary) and the legacy lax.Precision names
# that remain valid everywhere a mode string is accepted.
MODES = ("f32", "bf16x3", "bf16")
LEGACY = ("default", "high", "highest")

FAMILIES = ("covariance", "pca", "kmeans", "logistic", "linear", "serving")

PRECISION_ENV = "TPUML_PRECISION"
PRECISION_KNOB = "precision_mode"  # tune-store knob name

# Documented parity bounds vs the f32 reference (max |err| / max |ref|).
# These are the autotuner's COMMIT bars and the test-suite tolerances.
REL_TOL = {"bf16x3": 2e-4, "bf16": 3e-2}

# bf16 passes each mode spends per GEMM product — the roofline currency:
# a mode's achievable flops ceiling is bf16_peak / passes.
PASSES = {"f32": 6, "highest": 6, "high": 3, "bf16x3": 3, "default": 1, "bf16": 1}

# Registered-for-tests modes: name -> (dot callable, parity rel tol).
# The seeded parity-violating mode the autotuner must reject lives here.
_TEST_MODES: Dict[str, Tuple[Callable, float]] = {}

# family -> last resolved mode, consumed by the cost-ledger roofline so
# utilization prices against the ACTIVE policy's peak (ISSUE 17 sat. 1).
_ACTIVE_MODES: Dict[str, str] = {}


def register_test_mode(name: str, dot: Callable, rel_tol: float = 0.0) -> None:
    """Install a synthetic precision mode (tests only): ``dot(a, b)``
    replaces the GEMM, ``rel_tol`` is its parity bar for the tuner."""
    _TEST_MODES[name] = (dot, float(rel_tol))


def clear_test_modes() -> None:
    _TEST_MODES.clear()


def valid_modes() -> tuple:
    return MODES + LEGACY + tuple(_TEST_MODES)


def validate_mode(value: str) -> str:
    if value not in valid_modes():
        raise ValueError(
            f"precision mode must be one of {'/'.join(MODES + LEGACY)}, "
            f"got {value!r}"
        )
    return value


def split_hi_lo(a):
    """bf16 hi/lo split in f32 containers: a == hi + lo exactly, with
    ``hi`` the bf16 rounding of ``a`` (bf16-representable, so its
    DEFAULT-precision products are exact) and ``lo`` the residual
    carrying the next mantissa bits (|lo| <= 2^-9 |a|; its own bf16
    rounding inside a DEFAULT dot is the mode's error term, inside the
    documented :data:`REL_TOL` bound). NOT safe on non-finite values:
    hi(inf) = inf and lo = inf - inf = NaN — which is why sentinel
    constants on compensated paths must stay finite."""
    hi = a.astype(jnp.bfloat16).astype(a.dtype)
    return hi, a - hi


def _dot_bf16x3(a, b):
    if jnp.result_type(a, b) == jnp.float64:
        # Compensated modes target f32 data; under x64 the reference
        # numerics ARE native f64 — keep them.
        return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
    a_hi, a_lo = split_hi_lo(a)
    b_hi, b_lo = split_hi_lo(b)
    d = partial(
        jnp.matmul,
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32,
    )
    return d(a_hi, b_hi) + d(a_hi, b_lo) + d(a_lo, b_hi)


def _dot_bf16(a, b):
    if jnp.result_type(a, b) == jnp.float64:
        return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
    return jnp.matmul(
        a.astype(jnp.bfloat16),
        b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )


def make_dot(precision: str) -> Callable:
    """The ONE chokepoint mapping a mode name to a matmul-like callable.

    Legacy names and ``f32`` return a plain ``jnp.matmul`` closure at the
    corresponding ``lax.Precision`` — the SAME primitive sequence as
    before this layer existed, so the default policy is bit-identical.
    ``precision`` is static at every call site (jit static argname), so
    the choice resolves at trace time."""
    if precision in _TEST_MODES:
        return _TEST_MODES[precision][0]
    if precision == "bf16x3":
        return _dot_bf16x3
    if precision == "bf16":
        return _dot_bf16
    legacy = "highest" if precision == "f32" else precision
    return partial(jnp.matmul, precision=_dot_precision(legacy))


def as_dot(dot) -> Callable:
    """Coerce any historical precision spelling to a matmul callable:
    a callable passes through, a mode name goes through
    :func:`make_dot`, and a bare ``lax.Precision`` enum (the
    pre-policy-layer currency some helpers were called with) wraps into
    a plain matmul at that precision."""
    if isinstance(dot, str):
        return make_dot(dot)
    if callable(dot):
        return dot
    return partial(jnp.matmul, precision=dot)


def pdot(a, b, precision: str = "f32"):
    """Policy-aware matmul — ``jnp.matmul`` with a mode name."""
    return make_dot(precision)(a, b)


def pallas_precision(precision: str) -> str:
    """Map a policy mode onto the pallas kernels' precision vocabulary.

    The fused/packed KMeans kernels already implement the 3-pass
    compensated split as their ``"high"`` emulation (Mosaic has no HIGH
    mapping), so ``bf16x3`` lowers to exactly that code path."""
    return {"f32": "highest", "bf16x3": "high", "bf16": "default"}.get(
        precision, precision
    )


def mode_passes(mode: str) -> Optional[int]:
    return PASSES.get(mode)


# ---------------------------------------------------------------------------
# active-mode registry — the roofline's source of truth
# ---------------------------------------------------------------------------


def note_mode(family: str, mode: str) -> None:
    """Record the mode a family resolved to — consumed by
    :func:`roofline_peak_scale` so ``fit_report()``/``tpuml_prof`` price
    utilization against the active policy's peak."""
    _ACTIVE_MODES[family] = mode


def active_modes() -> Dict[str, str]:
    """Copy of the full family -> resolved-mode registry (the cost
    ledger snapshots this into its dump for offline renderers)."""
    return dict(_ACTIVE_MODES)


# Ledger program families for forward passes (kmeans.predict,
# pca.transform, …) run under the SERVING policy, not the fit family the
# prefix would suggest.
SERVING_SUFFIXES = ("predict", "transform", "serve")


def active_mode(family: str) -> Optional[str]:
    """Last resolved mode for ``family``; ledger program families carry
    a dotted suffix (e.g. ``kmeans.lloyd``) — a serving suffix maps to
    the ``serving`` policy, anything else falls back to the bare family
    prefix."""
    mode = _ACTIVE_MODES.get(family)
    if mode is None and "." in family:
        if family.rsplit(".", 1)[1] in SERVING_SUFFIXES:
            mode = _ACTIVE_MODES.get("serving")
        if mode is None:
            mode = _ACTIVE_MODES.get(family.split(".", 1)[0])
    return mode


def roofline_peak_scale(program_family: str) -> float:
    """Factor to multiply the declared ``TPUML_PEAK_FLOPS`` by for a
    ledger program family: the declared peak is the fp32 (6-pass)
    ceiling, and a mode spending fewer bf16 passes has proportionally
    more headroom (bf16x3 → 2x, bf16 → 6x). 1.0 when no mode was ever
    recorded for the family — exactly the pre-policy behavior."""
    mode = active_mode(program_family)
    if mode is None:
        return 1.0
    passes = PASSES.get(mode)
    if not passes:
        return 1.0
    return PASSES["f32"] / passes


def reset_for_tests() -> None:
    _ACTIVE_MODES.clear()
    _TEST_MODES.clear()


# ---------------------------------------------------------------------------
# policy resolution
# ---------------------------------------------------------------------------


def family_env(family: str) -> str:
    return f"TPUML_PRECISION_{family.upper()}"


def _env_mode(name: str) -> Optional[str]:
    from spark_rapids_ml_tpu.utils.envknobs import EnvKnobError, env_str

    value = env_str(name)
    if value is None:
        return None
    if value not in valid_modes():
        raise EnvKnobError(name, value, f"one of {'|'.join(MODES + LEGACY)}")
    return value


def resolve_policy(
    family: str, requested: Optional[str] = None, default: str = "highest"
) -> str:
    """Resolve the active precision mode for an op family.

    ``requested`` is the EXPLICITLY-set estimator param value (None when
    the user never called ``setPrecision``; ``"auto"``/``"dd"`` keep
    their pre-existing resolution and are passed through). Layering:
    explicit param > per-family env knob > global env knob > committed
    autotuner decision > ``default``."""
    if family not in FAMILIES:
        raise ValueError(f"unknown precision family {family!r}")
    if requested is not None and requested != "auto":
        # Explicit setPrecision wins outright; "dd" keeps its dedicated
        # double-double resolution downstream.
        mode = requested if requested == "dd" else validate_mode(requested)
        note_mode(family, mode)
        return mode
    mode = _env_mode(family_env(family)) or _env_mode(PRECISION_ENV)
    if mode is None and requested is None:
        from spark_rapids_ml_tpu.observability import autotune as _autotune

        tuner = _autotune.active()
        if tuner is not None:
            mode = tune_precision(family, tuner=tuner)
    if mode is None:
        mode = requested if requested is not None else default
    note_mode(family, mode)
    return mode


# ---------------------------------------------------------------------------
# autotuner gate
# ---------------------------------------------------------------------------

# Per-family candidate ladder, fastest-last. Fit families trial only the
# compensated mode (fits feed downstream math); serving may also trial
# plain bf16 (tolerance-insensitive predict paths).
_CANDIDATES = {"serving": ("bf16x3", "bf16")}
_DEFAULT_CANDIDATES = ("bf16x3",)

# Probe GEMM: big enough that the mode's pass count dominates the wall,
# small enough to amortize into one fit (~1 MFLOP-scale, compiled once).
_PROBE_M, _PROBE_K, _PROBE_N = 512, 256, 256


@partial(jax.jit, static_argnames=("mode",))
def _probe_gemm(a, b, mode: str):
    return pdot(a, b, mode)


def _probe_operands():
    rng = np.random.default_rng(0)
    a = jnp.asarray(
        rng.standard_normal((_PROBE_M, _PROBE_K)), dtype=jnp.float32
    )
    b = jnp.asarray(
        rng.standard_normal((_PROBE_K, _PROBE_N)), dtype=jnp.float32
    )
    return a, b


def _time_probe(a, b, mode: str, repeats: int = 3) -> tuple:
    import time

    out = _probe_gemm(a, b, mode)  # compile excluded from timing
    out.block_until_ready()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = _probe_gemm(a, b, mode)
        out.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return np.asarray(out), best


def candidate_rel_tol(mode: str) -> float:
    if mode in _TEST_MODES:
        return _TEST_MODES[mode][1]
    return REL_TOL.get(mode, 0.0)


def tune_precision(
    family: str, tuner=None, candidates: Optional[tuple] = None
) -> Optional[str]:
    """Trial faster precision modes for ``family`` through the autotuner
    and return the committed mode (or None when the tuner is off).

    The f32 reference runs first and seeds the incumbent; each candidate
    then commits iff its measured probe wall BEATS the incumbent AND its
    max relative error vs the f32 result stays within the documented
    bound (:data:`REL_TOL`). A slower candidate is recorded rejected
    with reason ``regression``; an out-of-bound one with reason
    ``parity`` — and the incumbent stands. Decisions persist in the tune
    store, so the probe runs once per (family, store)."""
    if tuner is None:
        from spark_rapids_ml_tpu.observability import autotune as _autotune

        tuner = _autotune.active()
        if tuner is None:
            return None
    decision = tuner.store.get(PRECISION_KNOB, family)
    if decision is not None:
        value = decision.get("value")
        return str(value) if value else None

    a, b = _probe_operands()
    shape = f"{_PROBE_M}x{_PROBE_K}x{_PROBE_N}"
    ref, wall_ref = _time_probe(a, b, "f32")
    tuner.record_trial(
        PRECISION_KNOB, family, "f32", wall_ref,
        evidence=[f"probe={shape}"], metric_name="probe_seconds",
    )
    scale = float(np.max(np.abs(ref))) or 1.0
    for mode in candidates or _CANDIDATES.get(family, _DEFAULT_CANDIDATES):
        res, wall = _time_probe(a, b, mode)
        err = float(np.max(np.abs(res - ref))) / scale
        tol = candidate_rel_tol(mode)
        tuner.record_trial(
            PRECISION_KNOB, family, mode, wall,
            evidence=[f"probe={shape}", f"max_rel_err={err:.3e}", f"tol={tol:.1e}"],
            metric_name="probe_seconds",
            ok=err <= tol,
            reason="parity",
        )
    decision = tuner.store.get(PRECISION_KNOB, family)
    if decision is None:
        return None
    value = decision.get("value")
    return str(value) if value else None
