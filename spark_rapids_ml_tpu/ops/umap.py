"""UMAP kernels — fuzzy simplicial set + batched SGD layout, all on-chip.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2; the modern RAPIDS Spark-ML line grew UMAP on cuML). The cuML lineage
optimizes the layout with per-edge sequential SGD (scatter races resolved by
atomics); the TPU-first formulation instead runs *synchronous* epochs: every
epoch applies ALL attractive edge gradients and a fresh draw of negative
samples in one fused program — gathers + elementwise + two scatter-adds —
inside a ``lax.fori_loop``. Shapes are static (E = n * k edges, E * m
negatives), determinism comes for free, and the annealed learning rate plays
the role of umap-learn's per-edge epoch scheduling (edge sample frequency ∝
membership weight becomes a per-edge gradient weight).

Graph construction reuses the exact kNN GEMM kernels (:mod:`ops.knn`); the
smooth-kNN sigma search is a vectorized 64-step bisection over all points at
once instead of umap-learn's per-point Python loop.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class FuzzyGraph(NamedTuple):
    """Directed kNN edge list with symmetrized membership weights.

    ``weight[i, j]`` is the probabilistic t-conorm w_ij + w_ji - w_ij * w_ji,
    halved for mutual edges (which appear in both endpoints' lists) so each
    undirected edge carries its weight exactly once across the edge set.
    """

    indices: jax.Array  # (n, k) int32 neighbor ids
    weight: jax.Array  # (n, k) float32 symmetrized membership
    sigmas: jax.Array  # (n,) smooth-kNN bandwidths
    rhos: jax.Array  # (n,) distance to nearest neighbor


@partial(jax.jit, static_argnames=("n_iter",))
def smooth_knn_dist(
    knn_dists: jax.Array, k: float, n_iter: int = 64
) -> Tuple[jax.Array, jax.Array]:
    """Per-point bandwidth sigma and connectivity offset rho.

    Solves sum_j exp(-max(d_ij - rho_i, 0) / sigma_i) = log2(k) for every
    point simultaneously by bisection — the all-points-at-once analogue of
    umap-learn's smooth_knn_dist loop.
    """
    target = jnp.log2(k)
    # rho: smallest positive neighbor distance (umap-learn with
    # local_connectivity=1).
    pos = jnp.where(knn_dists > 0, knn_dists, jnp.inf)
    rho = jnp.min(pos, axis=1)
    rho = jnp.where(jnp.isfinite(rho), rho, 0.0)

    def psum(sigma):
        return jnp.sum(
            jnp.exp(-jnp.maximum(knn_dists - rho[:, None], 0.0) / sigma[:, None]),
            axis=1,
        )

    lo = jnp.full(knn_dists.shape[0], 1e-12, knn_dists.dtype)
    # Bracket expansion (umap-learn doubles hi until the target is
    # bracketed): a fixed cap would silently saturate on data whose
    # distance scale is large, collapsing all memberships toward zero.
    hi = jnp.full(knn_dists.shape[0], 1.0, knn_dists.dtype)

    def expand(_, hi):
        return jnp.where(psum(hi) < target, hi * 2.0, hi)

    hi = lax.fori_loop(0, 48, expand, hi)  # 2^48 spans any float32 scale

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) / 2.0
        too_high = psum(mid) > target  # sum decreases as sigma shrinks
        return jnp.where(too_high, lo, mid), jnp.where(too_high, mid, hi)

    lo, hi = lax.fori_loop(0, n_iter, body, (lo, hi))
    sigma = (lo + hi) / 2.0
    # Floor, as in umap-learn: sigma no smaller than 1e-3 * mean distance.
    mean_d = jnp.mean(knn_dists)
    return jnp.maximum(sigma, 1e-3 * mean_d), rho


@jax.jit
def fuzzy_simplicial_set(knn_idx: jax.Array, knn_dists: jax.Array) -> FuzzyGraph:
    """Membership strengths + symmetrization over the directed kNN edges.

    The reverse weight w_ji is looked up by scanning j's neighbor list for i
    (a (n, k, k) compare — O(n k^2) elementwise, negligible next to the kNN
    GEMM); absent reverse edges contribute 0, exactly like the sparse
    transpose in umap-learn/cuML.
    """
    n, k = knn_idx.shape
    sigmas, rhos = smooth_knn_dist(knn_dists, float(k))
    w = jnp.exp(
        -jnp.maximum(knn_dists - rhos[:, None], 0.0) / sigmas[:, None]
    )  # (n, k) directed memberships

    # Reverse lookup: for edge (i -> j), find i in row j of knn_idx.
    src = jnp.broadcast_to(jnp.arange(n, dtype=knn_idx.dtype)[:, None], (n, k))
    rows_j = knn_idx  # (n, k): the j of each edge
    match = knn_idx[rows_j] == src[:, :, None]  # (n, k, k)
    w_rev_rows = w[rows_j]  # (n, k, k): weights of j's edges
    w_ji = jnp.sum(jnp.where(match, w_rev_rows, 0.0), axis=2)
    mutual = jnp.any(match, axis=2)

    w_sym = w + w_ji - w * w_ji
    w_sym = jnp.where(mutual, 0.5 * w_sym, w_sym)
    return FuzzyGraph(knn_idx.astype(jnp.int32), w_sym.astype(jnp.float32), sigmas, rhos)


def find_ab_params(spread: float, min_dist: float) -> Tuple[float, float]:
    """Fit the rational low-dimensional similarity curve 1/(1 + a d^2b) to
    the desired (min_dist, spread) offset-exponential — same least-squares
    target as umap-learn."""
    from scipy.optimize import curve_fit

    xv = np.linspace(0, spread * 3, 300)
    yv = np.where(
        xv < min_dist, 1.0, np.exp(-(xv - min_dist) / spread)
    )

    def curve(x, a, b):
        return 1.0 / (1.0 + a * x ** (2 * b))

    (a, b), _ = curve_fit(curve, xv, yv, p0=[1.0, 1.0], maxfev=10000)
    return float(a), float(b)


@partial(
    jax.jit,
    static_argnames=(
        "n_epochs", "neg_rate", "neg_pool", "move_other", "tail_cfg",
        "tail_interpret",
    ),
)
def optimize_layout(
    embedding: jax.Array,  # (n, dim) initial layout
    graph: FuzzyGraph,
    key: jax.Array,
    *,
    n_epochs: int,
    neg_rate: int = 5,
    neg_pool: int = 256,
    learning_rate: float = 1.0,
    repulsion: float = 1.0,
    a: float = 1.577,
    b: float = 0.895,
    move_other: bool = True,
    target: jax.Array | None = None,
    tail_plan=None,
    tail_cfg=None,
    tail_interpret: bool = False,
) -> jax.Array:
    """Synchronous-epoch UMAP layout optimization.

    Every epoch: gradients of the fuzzy cross-entropy for all E edges
    (attraction, weighted by membership) and a repulsion term from
    uniformly drawn negatives, applied with a linearly annealed step —
    umap-learn's sampling schedule folded into weights. ``target`` (if
    given) is a fixed reference point set the tail of each edge attracts
    to instead of the live embedding — the transform-time mode where
    train points stay put; ``move_other=False`` then skips the tail
    update.

    TPU layout (r4, measured 97% of the UMAP fit wall before): the edge
    list is EXACTLY (n heads x k neighbors), so every head-side access is
    STRUCTURED — the head "gather" is a broadcast of y and the head
    "scatter" is a dense (n, k, ...) sum over k — leaving only the
    genuinely random accesses on the slow scalarized path.

    Negative sampling (r5): ``neg_pool > 0`` (default) replaces the
    E * neg_rate per-edge random gathers — measured 96% of the fit wall
    in r4 (BASELINE config 13) — with ONE shared pool of ``neg_pool``
    uniform draws per epoch. Repulsion of every head against the pool is
    dense algebra: squared distances via ``y @ pool.T`` (MXU GEMM) plus
    norm broadcasts, and because the per-sample coefficient (not the
    per-component gradient) carries the clip, the gradient factorizes as
    ``rowsum(c) * y - c @ pool`` — two dense contractions, no gather.
    The estimator stays unbiased w.r.t. the per-edge one: each head's
    k * neg_rate uniform draws with per-edge weights w_ij are replaced
    by neg_pool shared uniform draws importance-weighted by
    sum_j(w_ij) * neg_rate / neg_pool, and the clip cap scales by the
    same ratio so the maximum per-epoch repulsion magnitude is preserved
    (cap * n_samples is invariant). Pool samples are shared across heads
    (correlated within an epoch, fresh draw every epoch); per-head
    expectation and total weight match the per-edge formulation exactly.
    ``neg_pool=0`` keeps the legacy per-edge path.

    ``tail_plan``/``tail_cfg`` (from :func:`ops.pallas.umap.
    build_tail_plan`) replace the per-epoch tail scatter-add with the
    Pallas bucketed-accumulation kernel over the tail-sorted static edge
    list (VERDICT r5 #1: the scatter was ~70% of the SGD wall). Tolerance
    parity with the scatter path — in-tile accumulation order differs.
    """
    n, dim = embedding.shape
    epoch = _make_epoch_fn(
        embedding.shape, graph, target,
        n_epochs=n_epochs, neg_rate=neg_rate, neg_pool=neg_pool,
        learning_rate=learning_rate, repulsion=repulsion, a=a, b=b,
        move_other=move_other, tail_plan=tail_plan, tail_cfg=tail_cfg,
        tail_interpret=tail_interpret,
    )
    y, _ = lax.fori_loop(0, n_epochs, epoch, (embedding, key))
    return y


def _make_epoch_fn(
    shape, graph: FuzzyGraph, target,
    *, n_epochs, neg_rate, neg_pool, learning_rate, repulsion, a, b, move_other,
    tail_plan=None, tail_cfg=None, tail_interpret=False,
):
    """Build ONE epoch of the synchronous layout SGD — the single home of
    the epoch body, closed over by the monolithic :func:`optimize_layout`
    program and the segmented :func:`_layout_segment` program so both run
    literally the same per-epoch math (checkpoint bit-identity; a tail
    plan, when given, is shared by both, so the invariant survives the
    Pallas tail path too)."""
    n, dim = shape
    k = graph.indices.shape[1]
    dst = graph.indices  # (n, k)
    w = graph.weight  # (n, k)
    n_ref = n if target is None else target.shape[0]
    w_sum = jnp.sum(w, axis=1)  # (n,) total edge weight per head

    def epoch(ep, carry):
        y, key = carry
        key, k_neg = jax.random.split(key)
        alpha = learning_rate * (1.0 - ep / n_epochs)

        # Edge gathers stay in ROW form — measured on v5e (r5): splitting
        # the (n, k, dim) gather into dim flat (n,) -> (n, k) lookups is
        # 1.5x SLOWER (scalar gathers pay per element; the row gather
        # amortizes index handling across the dim-wide row), the opposite
        # of the forest per-class-gather lesson, whose tables are
        # hundreds wide.
        yi = y[:, None, :]  # (n, 1, dim) — the head side is a broadcast
        ref_y = y if target is None else target
        yj = ref_y[dst]  # (n, k, dim)
        diff = yi - yj
        d2 = jnp.sum(diff * diff, axis=2)  # (n, k)
        # Attractive: d/dy_i of log(1/(1 + a d^2b)) -> -2ab d^{2(b-1)}/(1+a d^2b)
        att = (-2.0 * a * b * jnp.power(jnp.maximum(d2, 1e-12), b - 1.0)) / (
            1.0 + a * jnp.power(d2, b)
        )
        g_att = jnp.clip((att * w)[:, :, None] * diff, -4.0, 4.0)  # (n, k, dim)

        if neg_pool > 0:
            # Shared pool: neg_pool gathers per epoch (vs n*k*neg_rate),
            # then repulsion is dense (n, s) work on the MXU/VPU.
            pool_idx = jax.random.randint(k_neg, (neg_pool,), 0, n_ref)
            pool = (y if target is None else target)[pool_idx]  # (s, dim)
            y2 = jnp.sum(y * y, axis=1)  # (n,)
            p2 = jnp.sum(pool * pool, axis=1)  # (s,)
            cross = y @ pool.T  # (n, s) GEMM
            d2n = jnp.maximum(y2[:, None] + p2[None, :] - 2.0 * cross, 0.0)
            rep = (2.0 * repulsion * b) / (
                (0.001 + d2n) * (1.0 + a * jnp.power(d2n, b))
            )
            # Importance weight: each pool sample stands for
            # k * neg_rate / s per-edge draws of mean weight w_sum / k.
            c = rep * (w_sum[:, None] * (neg_rate / neg_pool))
            # Clip on the coefficient: per-edge path caps each of the
            # k * neg_rate per-sample gradients at 4; each pool sample
            # represents k * neg_rate / s of them, so cap scales by that
            # ratio (|c * diff| <= c * sqrt(d2n) <= cap).
            cap = 4.0 * k * neg_rate / neg_pool
            c = jnp.minimum(c, cap / jnp.sqrt(d2n + 1e-12))
            g_rep_head = (
                jnp.sum(c, axis=1, keepdims=True) * y - c @ pool
            )  # (n, dim): sum_p c_ip (y_i - pool_p), factorized
            grad_head = jnp.sum(g_att, axis=1) + g_rep_head
        else:
            # Legacy per-edge negatives: draw (E, m), view as (n, k, m).
            neg_idx = jax.random.randint(
                k_neg, (n * k, neg_rate), 0, n_ref
            ).reshape(n, k, neg_rate)
            # Negatives come from the LIVE layout in fit mode (repulsion
            # must track the moving points), frozen targets in transform.
            yn = ref_y[neg_idx]  # (n, k, m, dim)
            diff_n = y[:, None, None, :] - yn
            d2n = jnp.sum(diff_n * diff_n, axis=3)  # (n, k, m)
            rep = (2.0 * repulsion * b) / (
                (0.001 + d2n) * (1.0 + a * jnp.power(d2n, b))
            )
            g_rep = jnp.clip(
                (rep * w[:, :, None])[:, :, :, None] * diff_n, -4.0, 4.0
            )
            grad_head = jnp.sum(g_att + jnp.sum(g_rep, axis=2), axis=1)

        # Head moves along both terms (att < 0 pulls toward the neighbor,
        # rep > 0 pushes off the negatives): a DENSE sum — no scatter.
        # The tail mirrors attraction (true scatter, dst random) — unless
        # a tail plan routes it through the Pallas bucketed accumulator.
        delta = alpha * grad_head
        if move_other and target is None:
            tail_g = -alpha * g_att.reshape(-1, dim)
            if tail_plan is not None:
                from spark_rapids_ml_tpu.ops.pallas.umap import tail_accumulate

                delta = delta + tail_accumulate(
                    tail_g, tail_plan, tail_cfg, interpret=tail_interpret
                )
            else:
                delta = delta + jnp.zeros_like(y).at[dst.reshape(-1)].add(
                    tail_g
                )
        return y + delta, key

    return epoch


@partial(
    jax.jit,
    static_argnames=(
        "n_epochs", "neg_rate", "neg_pool", "move_other", "tail_cfg",
        "tail_interpret",
    ),
)
def _layout_segment(
    y, key_data, ep_start, ep_stop, graph: FuzzyGraph,
    learning_rate, repulsion, a, b, target, tail_plan=None,
    *, n_epochs: int, neg_rate: int, neg_pool: int, move_other: bool,
    tail_cfg=None, tail_interpret: bool = False,
):
    """Epochs [ep_start, ep_stop) of :func:`optimize_layout` from an
    explicit (layout, RNG) state — the checkpointable form. The RNG key
    travels as raw ``key_data`` (uint32) so the state pytree serializes;
    traced bounds keep ONE compiled program across all segments."""
    key = jax.random.wrap_key_data(key_data)
    epoch = _make_epoch_fn(
        y.shape, graph, target,
        n_epochs=n_epochs, neg_rate=neg_rate, neg_pool=neg_pool,
        learning_rate=learning_rate, repulsion=repulsion, a=a, b=b,
        move_other=move_other, tail_plan=tail_plan, tail_cfg=tail_cfg,
        tail_interpret=tail_interpret,
    )
    y, key = lax.fori_loop(ep_start, ep_stop, epoch, (y, key))
    return y, jax.random.key_data(key)


def optimize_layout_resumable(
    embedding: jax.Array,
    graph: FuzzyGraph,
    key: jax.Array,
    checkpointer,
    *,
    n_epochs: int,
    neg_rate: int = 5,
    neg_pool: int = 256,
    learning_rate: float = 1.0,
    repulsion: float = 1.0,
    a: float = 1.577,
    b: float = 0.895,
    move_other: bool = True,
    target: jax.Array | None = None,
    tail_plan=None,
    tail_cfg=None,
    tail_interpret: bool = False,
) -> jax.Array:
    """Preemption-tolerant :func:`optimize_layout`: ``checkpointer.every``
    epochs per jitted segment, the (layout, RNG key data, epoch) state
    snapshotted asynchronously between segments, resumed mid-schedule
    from the latest valid checkpoint. Bit-identical final layout."""
    from spark_rapids_ml_tpu.robustness.checkpoint import segment_boundary
    import time

    from spark_rapids_ml_tpu.observability.costs import ledgered_call
    from spark_rapids_ml_tpu.observability.metrics import observe_segment_seconds
    from spark_rapids_ml_tpu.robustness.faults import fault_point
    from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange, bump_counter

    state = (embedding, jax.random.key_data(key), jnp.asarray(0))
    restored = checkpointer.restore_latest(template=state)
    if restored is not None:
        _, state = restored
    y, kd, ep = state
    while int(ep) < n_epochs:
        start = int(ep)
        stop = min(start + checkpointer.every, n_epochs)
        seg_t0 = time.perf_counter()
        with TraceRange("segment umap.layout", TraceColor.PURPLE):
            fault_point("solver.segment")
            y, kd = ledgered_call(
                _layout_segment,
                (y, kd, jnp.asarray(start), jnp.asarray(stop), graph,
                 learning_rate, repulsion, a, b, target, tail_plan),
                static=dict(
                    n_epochs=n_epochs, neg_rate=neg_rate, neg_pool=neg_pool,
                    move_other=move_other, tail_cfg=tail_cfg,
                    tail_interpret=tail_interpret,
                ),
                name="umap.layout.segment",
            )
            ep = jnp.asarray(stop)
            bump_counter("checkpoint.segments")
            bump_counter("checkpoint.solver_iters", stop - start)
        observe_segment_seconds("umap.layout", time.perf_counter() - seg_t0)
        checkpointer.save_async(stop, (y, kd, ep))
        segment_boundary(checkpointer)
    checkpointer.finalize_success()
    return y


@lru_cache(maxsize=None)
def _sharded_layout_fn(
    mesh, n: int, k_nbrs: int, n_epochs: int, neg_rate: int, neg_pool: int
):
    """Build (and cache) the jitted shard_map epoch program for one
    (mesh, shape) combination — jit's cache is keyed on the function
    object, so the closure must not be rebuilt per call (the
    knn/ann/dbscan cached-builder pattern). Float hyperparameters enter
    as TRACED scalars, not cache keys: a tuning sweep over learning rate
    or min_dist must reuse one executable, not pin one per float value.
    """
    from jax.sharding import PartitionSpec as P
    from spark_rapids_ml_tpu.utils.compat import axis_size, shard_map

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    def local(dst_b, w_b, y0, key, learning_rate, repulsion, a, b):
        # Edges shard by HEAD ROW (n_local, k) — the same structured-head
        # layout as the single-device epoch: the head gather is a
        # dynamic slice of y, the head scatter a dense sum + one
        # dynamic-update-slice; only the dst/negative gathers and the
        # tail scatter stay on the scalarized path.
        #
        # Pooled mode (neg_pool > 0) draws the shared pool from the UNFOLDED
        # (replicated) key so every shard scores the identical pool — no
        # per-shard randomness remains, and the epoch matches the
        # single-device pooled path up to psum reduction order. Only the
        # legacy per-edge path folds the key per shard.
        shard_key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        if neg_pool <= 0:
            key = shard_key
        n_local = dst_b.shape[0]
        row0 = lax.axis_index(DATA_AXIS) * n_local
        n_pad_total = n_local * axis_size(DATA_AXIS)
        dim = y0.shape[1]
        w_sum_b = jnp.sum(w_b, axis=1)  # (n_local,)

        def epoch(ep, carry):
            y, key = carry
            key, k_neg = jax.random.split(key)
            alpha = learning_rate * (1.0 - ep / n_epochs)
            yh = lax.dynamic_slice_in_dim(y, row0, n_local)  # (n_local, dim)
            # Row gather, as in the single-device epoch (r5 measured the
            # component-split variant 1.5x SLOWER on v5e).
            yj = y[dst_b]  # (n_local, k, dim)
            diff = yh[:, None, :] - yj
            d2 = jnp.sum(diff * diff, axis=2)
            att = (-2.0 * a * b * jnp.power(jnp.maximum(d2, 1e-12), b - 1.0)) / (
                1.0 + a * jnp.power(d2, b)
            )
            g_att = jnp.clip((att * w_b)[:, :, None] * diff, -4.0, 4.0)
            if neg_pool > 0:
                pool_idx = jax.random.randint(k_neg, (neg_pool,), 0, n)
                pool = y[pool_idx]  # (s, dim) — y is replicated
                yh2 = jnp.sum(yh * yh, axis=1)
                p2 = jnp.sum(pool * pool, axis=1)
                cross = yh @ pool.T  # (n_local, s)
                d2n = jnp.maximum(
                    yh2[:, None] + p2[None, :] - 2.0 * cross, 0.0
                )
                rep = (2.0 * repulsion * b) / (
                    (0.001 + d2n) * (1.0 + a * jnp.power(d2n, b))
                )
                c = rep * (w_sum_b[:, None] * (neg_rate / neg_pool))
                cap = 4.0 * k_nbrs * neg_rate / neg_pool
                c = jnp.minimum(c, cap / jnp.sqrt(d2n + 1e-12))
                g_rep_head = jnp.sum(c, axis=1, keepdims=True) * yh - c @ pool
                grad_head = jnp.sum(g_att, axis=1) + g_rep_head
            else:
                neg_idx = jax.random.randint(
                    k_neg, (n_local, k_nbrs, neg_rate), 0, n
                )
                yn = y[neg_idx]  # (n_local, k, m, dim)
                diff_n = yh[:, None, None, :] - yn
                d2n = jnp.sum(diff_n * diff_n, axis=3)
                rep = (2.0 * repulsion * b) / (
                    (0.001 + d2n) * (1.0 + a * jnp.power(d2n, b))
                )
                g_rep = jnp.clip(
                    (rep * w_b[:, :, None])[:, :, :, None] * diff_n, -4.0, 4.0
                )
                grad_head = jnp.sum(g_att + jnp.sum(g_rep, axis=2), axis=1)
            delta = jnp.zeros_like(y).at[dst_b.reshape(-1)].add(
                -alpha * g_att.reshape(-1, dim)
            )
            head_block = (
                lax.dynamic_slice_in_dim(delta, row0, n_local)
                + alpha * grad_head
            )
            delta = lax.dynamic_update_slice_in_dim(delta, head_block, row0, 0)
            # ONE collective per epoch: merge the shards' deltas so every
            # device applies the identical (replicated) update.
            delta = lax.psum(delta, DATA_AXIS)
            return y + delta, key

        # Pad y to the sharded row total so head slices never clamp;
        # padded rows carry zero weight and are never sampled (negatives
        # draw from [0, n)).
        y_pad = jnp.pad(y0, ((0, n_pad_total - n), (0, 0)))
        y_pad, _ = lax.fori_loop(0, n_epochs, epoch, (y_pad, key))
        return y_pad[:n]

    fit = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None), P(DATA_AXIS, None), P(), P(),
            P(), P(), P(), P(),
        ),
        out_specs=P(),
        check_vma=False,  # the psum-merged y is replicated by construction
    )
    return jax.jit(fit)


def optimize_layout_sharded(
    mesh,
    embedding: jax.Array,
    graph: FuzzyGraph,
    key: jax.Array,
    *,
    n_epochs: int,
    neg_rate: int = 5,
    neg_pool: int = 256,
    learning_rate: float = 1.0,
    repulsion: float = 1.0,
    a: float = 1.577,
    b: float = 0.895,
) -> jax.Array:
    """Mesh-sharded synchronous-epoch layout optimization (fit mode).

    The epoch shards edges by HEAD ROW over the mesh data axis (the
    structured-head layout of the single-device epoch: the head side of
    every edge is a slice/dense-sum, never a gather/scatter); each shard
    accumulates its gradient contributions into a local (n, dim) delta,
    and ONE psum per epoch merges the deltas over ICI — the embedding
    stays replicated, so the per-epoch wire cost is the (n, dim) delta,
    independent of edge count (VERDICT r1 missing item 6: previously
    only the kNN-graph stage sharded).

    Pooled negatives (``neg_pool > 0``, default) draw ONE shared pool per
    epoch from the replicated key, so all shards score the identical pool
    and the result matches the single-device pooled path up to psum
    reduction order. The legacy per-edge path (``neg_pool=0``) draws
    negatives per shard (key folded with the shard index): same sampling
    distribution and count per edge, different RNG stream — like any
    reseeded SGD run.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    n, dim = embedding.shape
    k = graph.indices.shape[1]
    dst = graph.indices  # (n, k)
    w = graph.weight
    dp = int(mesh.shape[DATA_AXIS])
    pad = (-n) % dp
    if pad:
        # Padded head rows carry zero weight: their attractive AND
        # repulsive terms are scaled by w, so they contribute nothing.
        dst = jnp.concatenate([dst, jnp.zeros((pad, k), jnp.int32)])
        w = jnp.concatenate([w, jnp.zeros((pad, k), w.dtype)])

    row_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    dst = jax.device_put(dst, row_sharding)
    w = jax.device_put(w, row_sharding)
    y0 = jax.device_put(embedding.astype(jnp.float32), NamedSharding(mesh, P()))

    fit = _sharded_layout_fn(mesh, n, k, n_epochs, neg_rate, neg_pool)
    f32 = jnp.float32
    return fit(
        dst, w, y0, key,
        jnp.asarray(learning_rate, f32), jnp.asarray(repulsion, f32),
        jnp.asarray(a, f32), jnp.asarray(b, f32),
    )


def spectral_init(
    graph: FuzzyGraph, n: int, dim: int, key: jax.Array
) -> jax.Array:
    """Normalized-Laplacian spectral embedding of the fuzzy graph (dense —
    one symmetric eigh on the device; used below a size cap, random init
    above it). Scaled to the ±10 box with a small noise break, as in
    umap-learn."""
    w = jnp.zeros((n, n), dtype=jnp.float32)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], graph.indices.shape)
    w = w.at[src.reshape(-1), graph.indices.reshape(-1)].add(graph.weight.reshape(-1))
    w = w + w.T  # undirected (weights were already de-duplicated for mutuals)
    deg = jnp.maximum(jnp.sum(w, axis=1), 1e-8)
    d_inv_sqrt = 1.0 / jnp.sqrt(deg)
    lap = jnp.eye(n, dtype=jnp.float32) - d_inv_sqrt[:, None] * w * d_inv_sqrt[None, :]
    vals, vecs = jnp.linalg.eigh(lap)
    emb = vecs[:, 1 : dim + 1]  # skip the trivial constant eigenvector
    expansion = 10.0 / jnp.maximum(jnp.max(jnp.abs(emb)), 1e-8)
    noise = jax.random.normal(key, emb.shape, dtype=emb.dtype) * 1e-4
    return emb * expansion + noise
