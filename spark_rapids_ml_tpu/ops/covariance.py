"""Covariance kernels — fused center+scale+GEMM as single XLA executables.

Reference pipeline (RapidsRowMatrix.scala:149-257): per-row JVM centering
(:176-182, HOT LOOP 1), concat to row-major B (:183-189), JNI dgemm C=BᵀB
(:195), Spark reduce of n×n partials (:201). SURVEY.md §7 flags the per-row
JVM centering as the thing that belongs *inside* the compiled program on TPU —
here centering, scaling and the rank-k update are one jitted computation that
XLA fuses; there is no host-side row loop at all.

Normalization: the reference GEMM path scales by 1/√(numCols−1) while the spr
path divides by numRows−1 (RapidsRowMatrix.scala:169 vs :240-246) — a quirk
SURVEY.md §7 says to fix, not copy. Both paths here normalize by (n_rows − 1).
PCA outputs are invariant to the scalar, so the test oracle is unaffected.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_ml_tpu.ops.linalg import _triu_indices_packed
from spark_rapids_ml_tpu.ops.precision import make_dot


@partial(jax.jit, static_argnames=("precision",))
def centered_gram(x: jax.Array, mean: jax.Array, precision: str = "highest") -> jax.Array:
    """(x − mean)ᵀ(x − mean) — the per-partition covariance partial.

    This is the distributed unit of work: each data shard computes its local
    centered Gram against the *global* mean (broadcast, like
    RapidsRowMatrix.scala:162), and partials are summed by a collective.
    """
    b = x - mean
    return make_dot(precision)(b.T, b)


@partial(jax.jit, static_argnames=("precision",))
def mean_and_covariance(x: jax.Array, precision: str = "highest"):
    """Single-device fused path: returns (column means, covariance).

    Covariance normalized by (n − 1), matching the spr/treeAggregate path
    (RapidsRowMatrix.scala:240-246) — the statistically correct sample
    covariance.
    """
    n = x.shape[0]
    mean = jnp.mean(x, axis=0)
    cov = centered_gram(x, mean, precision=precision) / (n - 1)
    return mean, cov


def covariance(x: jax.Array, precision: str = "highest") -> jax.Array:
    return mean_and_covariance(x, precision=precision)[1]


@partial(jax.jit, static_argnames=("block_rows", "precision"))
def centered_gram_blocked(
    x: jax.Array, mean: jax.Array, block_rows: int = 4096, precision: str = "highest"
) -> jax.Array:
    """Streaming centered Gram over row blocks via lax.scan.

    For row counts whose (n, d) activation would not fit HBM alongside the
    result, accumulate BᵀB block-by-block. Padding rows are filled with
    ``mean`` so their centered contribution is exactly zero — no masking
    needed inside the scan body, keeping the MXU matmul dense and static.
    """
    n, d = x.shape
    nb = -(-n // block_rows)
    pad = nb * block_rows - n
    x = jnp.concatenate([x, jnp.broadcast_to(mean, (pad, d))], axis=0) if pad else x
    blocks = x.reshape(nb, block_rows, d)
    dot = make_dot(precision)

    def body(acc, blk):
        b = blk - mean
        return acc + dot(b.T, b), None

    acc0 = jnp.zeros((d, d), dtype=x.dtype)
    acc, _ = jax.lax.scan(body, acc0, blocks)
    return acc


@jax.jit
def centered_gram_packed(x: jax.Array, mean: jax.Array) -> jax.Array:
    """Packed-upper-triangular centered Gram — the spr/treeAggregate path.

    Surface parity with the reference's packed accumulation
    (RapidsRowMatrix.scala:207-233, layout of cublasDspr FILL_MODE_UPPER).
    Computed as a full Gram then packed: on TPU a dense MXU matmul beats
    n_rows sequential rank-1 updates by orders of magnitude, so the packed
    layout is kept only as the aggregation/wire format (n ≤ 65535 constraint
    inherited from the layout, RapidsRowMatrix.scala:66-68).
    """
    full = centered_gram(x, mean)
    rows, cols = _triu_indices_packed(x.shape[1])
    return full[rows, cols]


def shifted_block_scan(blocks, center: bool, gram_fn, min_rows: int = 2):
    """Shared scaffold of the one-pass shifted covariance accumulations
    (this module's fp32/HIGHEST path and ops.doubledouble's dd path — ONE
    home for the streaming algebra).

    The exact mean is unknown until the stream ends, so blocks are centered
    on the FIRST block's column means (exact host-fp64 subtract — the
    shifted-accumulation scheme of the native Kahan runtime,
    native/src/tpuml_host.cpp, there for the reference's streamed
    ``mapPartitions`` contract, RapidsRowMatrix.scala:170); ``gram_fn``
    maps each shifted host block to its Gram contribution. Returns
    ``(shift, gram, s, n)`` — finish with :func:`finalize_shifted_gram`.
    """
    from spark_rapids_ml_tpu.core.data import _block_to_dense
    from spark_rapids_ml_tpu.core.serving import prefetch_blocks

    shift = gram = s = None
    n = 0
    # Double-buffered at the densify level: block k+1's host decode
    # (parquet batch → ndarray) overlaps block k's Gram program. The
    # shift itself comes from the FIRST block, so centering and upload
    # stay in the loop — values and order are bit-identical.
    for b in prefetch_blocks(blocks, _block_to_dense):
        if b.shape[0] == 0:
            continue
        if shift is None:
            shift = b.mean(axis=0) if center else np.zeros(b.shape[1])
        bs = b - shift
        g = gram_fn(bs)
        gram = g if gram is None else gram + g
        sb = bs.sum(axis=0)
        s = sb if s is None else s + sb
        n += b.shape[0]
    if n < min_rows:
        # min_rows=0 callers (per-process partial scans that merge across
        # processes) accept empty results — shift/gram/s are None then.
        raise ValueError(f"need at least 2 rows to compute a covariance, got {n}")
    return shift, gram, s, n


def finalize_shifted_gram(shift, gram, s, n, center: bool):
    """Recover (mean, cov, n) from a shifted scan: the closed-form
    correction ``Σx̃ᵀx̃ − n·δδᵀ`` (δ = mean of shifted values) yields the
    true centered Gram; with ``center=False`` the shift is identically zero
    so the accumulated Gram already IS the raw second moment. Cov is
    normalized by (n − 1)."""
    delta = s / n
    mean = shift + delta
    gram = np.asarray(gram, dtype=np.float64)
    if center:
        gram = gram - n * np.outer(delta, delta)
    return mean, gram / (n - 1), n


def streaming_mean_and_covariance(
    blocks, center: bool = True, dtype=None, precision: str = "highest"
):
    """ONE-pass covariance over an iterable of host blocks — the
    constant-memory fit path (each block visited exactly once, device
    memory bounded by one block + the (d, d) accumulator). Shifted Gram
    accumulates on the accelerator; returns host fp64 ``(mean, cov, n)``.
    """
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

    def gram_fn(bs):
        return centered_gram(
            jnp.asarray(bs, dtype=dtype),
            jnp.zeros(bs.shape[1], dtype=dtype),
            precision=precision,
        )

    return finalize_shifted_gram(*shifted_block_scan(blocks, center, gram_fn), center)


@lru_cache(maxsize=None)
def _sharded_block_gram(mesh, precision: str):
    """Cached jitted program: Gram of a row-sharded block with the
    replicated (d, d) result — XLA inserts one psum over the data axis
    per block (the cross-chip reduce of the streamed mesh covariance)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    dot = make_dot(precision)

    @partial(jax.jit, out_shardings=NamedSharding(mesh, P()))
    def gram(xs):
        return dot(xs.T, xs)

    return gram


def streaming_mean_and_covariance_mesh(
    blocks, mesh, center: bool = True, dtype=None, precision: str = "highest"
):
    """ONE-pass covariance over streamed host blocks, each block
    row-sharded over the mesh data axis — the north-star deployment loop
    (BASELINE config 5): stream from disk, shard each block over the
    chips, accumulate the replicated (d, d) Gram on device with one psum
    per block riding ICI. Host and per-device memory stay bounded by one
    block; the same shifted-accumulation algebra as the single-device
    streaming path. Returns host fp64 ``(mean, cov, n)``.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    if jax.process_count() > 1:
        raise ValueError(
            "this single-process sharded-block path has a multi-process "
            "sibling: parallel.distributed.streaming_covariance_process_local "
            "(each process streams its LOCAL blocks; RowMatrix routes there "
            "automatically)"
        )
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    dp = int(mesh.shape[DATA_AXIS])
    x_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    device_gram = _sharded_block_gram(mesh, precision)

    def gram_fn(bs):
        # Pad rows to the data-axis multiple with zeros — zero rows
        # contribute exactly nothing to the Gram (the caller's column sums
        # use the unpadded block).
        pad = (-bs.shape[0]) % dp
        if pad:
            # Match dtype: a default-f64 zeros block would upcast (and
            # copy) the whole concatenated block.
            bs = np.concatenate([bs, np.zeros((pad, bs.shape[1]), dtype=bs.dtype)])
        xs = jax.device_put(bs.astype(np.dtype(dtype), copy=False), x_sharding)
        return device_gram(xs)

    # One home for the streaming algebra: shifted_block_scan.
    return finalize_shifted_gram(*shifted_block_scan(blocks, center, gram_fn), center)


def welford_init(d: int, dtype=jnp.float64) -> tuple:
    """(count, mean, M2) accumulator for streaming column stats.

    The reference's mean pass is mllib ``Statistics.colStats``
    (RapidsRowMatrix.scala:156), a Welford-style treeAggregate. These three
    functions reproduce that contract for partitioned/distributed input.
    """
    return (
        jnp.zeros((), dtype=dtype),
        jnp.zeros((d,), dtype=dtype),
        jnp.zeros((d,), dtype=dtype),
    )


@jax.jit
def welford_add_block(state: tuple, x: jax.Array) -> tuple:
    count, mean, m2 = state
    n_b = x.shape[0]
    if n_b == 0:  # static shape: an empty partition contributes nothing
        return state
    mean_b = jnp.mean(x, axis=0)
    m2_b = jnp.sum((x - mean_b) ** 2, axis=0)
    new_count = count + n_b
    delta = mean_b - mean
    new_mean = mean + delta * (n_b / new_count)
    new_m2 = m2 + m2_b + delta**2 * (count * n_b / new_count)
    return (new_count, new_mean, new_m2)


@jax.jit
def welford_merge(a: tuple, b: tuple) -> tuple:
    count_a, mean_a, m2_a = a
    count_b, mean_b, m2_b = b
    count = count_a + count_b
    safe = jnp.maximum(count, 1)
    delta = mean_b - mean_a
    mean = mean_a + delta * (count_b / safe)
    m2 = m2_a + m2_b + delta**2 * (count_a * count_b / safe)
    return (count, mean, m2)
