"""DBSCAN kernels — blocked epsilon-graph sweeps + min-label propagation.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md §2;
the modern RAPIDS Spark-ML line grew DBSCAN on cuML). The cuML algorithm is
a vertex-degree + BFS frontier expansion over an adjacency structure; that
shape is host-sequential and pointer-chasing, which is exactly what a TPU is
bad at. TPU-first redesign:

  - The epsilon graph is never materialized. Every sweep recomputes blocked
    pairwise squared distances as (Bq, d) x (d, Bi) GEMMs on the MXU —
    FLOPs are cheap, HBM is not.
  - Core points: one sweep counting eps-neighbors (``core_point_mask``).
  - Clusters: connected components of the core-core epsilon graph via
    iterative **min-label diffusion** inside ``lax.while_loop``: every core
    point takes the minimum label over its core eps-neighbors, followed by
    pointer-jumping (``labels[labels]``) for near-logarithmic convergence —
    the classic shortcutting trick from parallel union-find, expressed as a
    gather so XLA can keep everything on-chip.
  - Border points attach to the minimum-label core neighbor in one final
    sweep; everything else is noise (-1).

All shapes are static: rows pad to a block multiple and ride a ``lax.scan``
over item blocks nested in a ``lax.map`` over query blocks, so one compiled
program serves any n at O(block_q * block_i) live memory.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_ml_tpu.ops.knn import _block_sq_distances
from spark_rapids_ml_tpu.ops.linalg import _dot_precision

_INT_MAX = jnp.iinfo(jnp.int32).max


def _pad_rows(x: jax.Array, block: int) -> Tuple[jax.Array, int]:
    n = x.shape[0]
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    return x, n_blocks


def _eps_sweep(x, valid, eps_sq, per_block, combine, init, block_q, block_i,
               prec, x_items=None, valid_items=None):
    """Generic blocked sweep over the epsilon graph.

    For every query block, scans all item blocks; ``per_block(adj, j0)``
    maps the (Bq, Bi) boolean adjacency (already masked to valid items,
    self-pairs INCLUDED) to a partial result, folded with ``combine`` from
    ``init``. Returns the per-query results concatenated to the padded
    query count. ``x_items``/``valid_items`` default to the query set; a
    distinct item set is the distributed case (local query shard against
    the replicated full point set).
    """
    if x_items is None:
        x_items, valid_items = x, valid
    xp, n_qblocks = _pad_rows(x, block_q)
    xi, n_iblocks = _pad_rows(x_items, block_i)
    validp = jnp.pad(valid, (0, xp.shape[0] - valid.shape[0]))
    validi = jnp.pad(valid_items, (0, xi.shape[0] - valid_items.shape[0]))
    item_blocks = xi.reshape(n_iblocks, block_i, -1)
    item_valid = validi.reshape(n_iblocks, block_i)
    j_starts = jnp.arange(n_iblocks, dtype=jnp.int32) * block_i

    def one_query_block(args):
        qb, qvalid = args
        q_sq = jnp.sum(qb * qb, axis=1)

        def step(carry, blk):
            xb, ivalid, j0 = blk
            d2 = _block_sq_distances(qb, xb, q_sq, prec)
            adj = (d2 <= eps_sq) & ivalid[None, :] & qvalid[:, None]
            return combine(carry, per_block(adj, j0)), None

        out, _ = lax.scan(step, init, (item_blocks, item_valid, j_starts))
        return out

    qblocks = xp.reshape(n_qblocks, block_q, -1)
    qvalids = validp.reshape(n_qblocks, block_q)
    outs = lax.map(one_query_block, (qblocks, qvalids))
    return outs.reshape((-1,) + outs.shape[2:])


@partial(jax.jit, static_argnames=("block_q", "block_i", "precision"))
def core_point_mask(
    x: jax.Array,
    eps: float,
    min_pts: int,
    row_mask: jax.Array | None = None,
    block_q: int = 2048,
    block_i: int = 8192,
    precision: str = "highest",
) -> jax.Array:
    """Boolean (n,) mask of core points: >= min_pts neighbors within eps.

    Neighbor counts include the point itself (sklearn/cuML convention).
    ``row_mask`` flags real rows (1) vs padding (0).
    """
    n = x.shape[0]
    valid = jnp.ones(n, bool) if row_mask is None else row_mask.astype(bool)
    eps_sq = jnp.asarray(eps, x.dtype) ** 2
    counts = _eps_neighbor_counts(
        x, valid, eps_sq, block_q, block_i, _dot_precision(precision)
    )[:n]
    return (counts >= min_pts) & valid


def _eps_neighbor_counts(x, valid, eps_sq, block_q, block_i, prec,
                         x_items=None, valid_items=None):
    """(padded_n,) eps-neighbor counts — the one home of the counting sweep
    (shared by the single-device and sharded paths)."""
    return _eps_sweep(
        x,
        valid,
        eps_sq,
        per_block=lambda adj, j0: jnp.sum(adj, axis=1, dtype=jnp.int32),
        combine=lambda a, b: a + b,
        init=jnp.zeros(block_q, jnp.int32),
        block_q=block_q,
        block_i=block_i,
        prec=prec,
        x_items=x_items,
        valid_items=valid_items,
    )


def _min_core_neighbor_label(x, valid, core, labels, eps_sq, block_q, block_i,
                             prec, x_items=None, valid_items=None):
    """For every point, min label over its CORE eps-neighbors (incl. itself
    when core). _INT_MAX where it has none. ``core``/``labels`` describe
    the ITEM set (= the query set in the single-device case)."""
    n = x.shape[0]
    labels_i, _ = _pad_rows(labels, block_i)
    core_i, _ = _pad_rows(core, block_i)

    def per_block(adj, j0):
        lab = lax.dynamic_slice(labels_i, (j0,), (adj.shape[1],))
        cor = lax.dynamic_slice(core_i, (j0,), (adj.shape[1],))
        masked = jnp.where(adj & cor[None, :], lab[None, :], _INT_MAX)
        return jnp.min(masked, axis=1)

    return _eps_sweep(
        x,
        valid,
        eps_sq,
        per_block=per_block,
        combine=jnp.minimum,
        init=jnp.full(block_q, _INT_MAX, jnp.int32),
        block_q=block_q,
        block_i=block_i,
        prec=prec,
        x_items=x_items,
        valid_items=valid_items,
    )[:n]


def _compress_labels(labels: jax.Array, core: jax.Array, n: int) -> jax.Array:
    """Pointer-jump ``labels[labels]`` to a FIXPOINT (full path compression).

    Labels are point indices, so ``labels[labels]`` hops to the
    representative's current representative (union-find shortcutting); each
    iteration doubles the compressed hop depth, so a chain of length L
    collapses in O(log L) cheap (n,) gathers. Running this to convergence
    between epsilon sweeps is what makes the number of EXPENSIVE O(n^2 d)
    sweeps O(log n) instead of O(cluster diameter) (VERDICT r4 #5 — a
    long-chain dataset previously degraded the sweep count arbitrarily).
    _INT_MAX entries clamp to a safe no-op gather.
    """

    def jcond(state):
        _, changed = state
        return changed

    def jbody(state):
        lab, _ = state
        safe = jnp.clip(lab, 0, n - 1)
        jumped = jnp.where(core, jnp.minimum(lab, lab[safe]), lab)
        return (jumped, jnp.any(jumped != lab))

    labels, _ = lax.while_loop(jcond, jbody, (labels, jnp.asarray(True)))
    return labels


@partial(
    jax.jit,
    static_argnames=("block_q", "block_i", "precision", "return_sweeps"),
)
def dbscan_labels(
    x: jax.Array,
    eps: float,
    min_pts: int,
    row_mask: jax.Array | None = None,
    block_q: int = 2048,
    block_i: int = 8192,
    precision: str = "highest",
    return_sweeps: bool = False,
):
    """Full DBSCAN: returns (labels (n,) int32, core_mask (n,) bool).

    Labels are cluster ids that are *representative point indices* (the
    minimum point index in each cluster's core set), -1 for noise. Use
    :func:`relabel_consecutive` on the host for 0..C-1 ids. Border points
    attach to their minimum-label core neighbor (deterministic; sklearn
    attaches to the first core neighbor in scan order, so individual border
    assignments may differ between ties — cluster *membership structure* of
    core points is identical).

    Each diffusion round is one epsilon sweep (blocked GEMMs, the expensive
    part) followed by pointer-jumping to a fixpoint (cheap (n,) gathers),
    so rounds grow O(log n) in the worst chain topology, not O(diameter).
    ``return_sweeps=True`` appends the number of epsilon sweeps executed
    (diffusion rounds + the final convergence-check round).
    """
    n = x.shape[0]
    valid = jnp.ones(n, bool) if row_mask is None else row_mask.astype(bool)
    eps_sq = jnp.asarray(eps, x.dtype) ** 2
    prec = _dot_precision(precision)

    core = core_point_mask(
        x, eps, min_pts, row_mask=valid, block_q=block_q, block_i=block_i, precision=precision
    )

    labels0 = jnp.where(core, jnp.arange(n, dtype=jnp.int32), _INT_MAX)

    def cond(state):
        labels, changed, _ = state
        return changed

    def body(state):
        labels, _, sweeps = state
        neigh = _min_core_neighbor_label(x, valid, core, labels, eps_sq, block_q, block_i, prec)
        new = jnp.where(core, jnp.minimum(labels, neigh), labels)
        jumped = _compress_labels(new, core, n)
        return (jumped, jnp.any(jumped != labels), sweeps + 1)

    labels, _, sweeps = lax.while_loop(
        cond, body, (labels0, jnp.asarray(True), jnp.zeros((), jnp.int32))
    )

    # Border attachment: non-core points take the min core-neighbor label.
    neigh = _min_core_neighbor_label(x, valid, core, labels, eps_sq, block_q, block_i, prec)
    border = (~core) & (neigh < _INT_MAX) & valid
    labels = jnp.where(border, neigh, labels)
    labels = jnp.where(labels == _INT_MAX, -1, labels)
    labels = jnp.where(valid, labels, -1)
    if return_sweeps:
        return labels, core, sweeps
    return labels, core


def relabel_consecutive(labels: np.ndarray) -> np.ndarray:
    """Host-side: map representative-index labels to consecutive 0..C-1,
    ordered by first appearance (sklearn convention); noise stays -1."""
    labels = np.asarray(labels)
    out = np.full_like(labels, -1)
    pos = np.flatnonzero(labels >= 0)
    if pos.size == 0:
        return out
    reps, inverse = np.unique(labels[pos], return_inverse=True)
    # Order clusters by first appearance: rank representatives by the
    # minimum row index at which each occurs.
    first_row = np.full(reps.size, labels.size, dtype=np.int64)
    np.minimum.at(first_row, inverse, pos)
    rank = np.empty(reps.size, dtype=np.int64)
    rank[np.argsort(first_row, kind="stable")] = np.arange(reps.size)
    out[pos] = rank[inverse]
    return out


import functools


@functools.lru_cache(maxsize=None)
def _sharded_dbscan_fn(mesh, n_tot: int, n_loc: int, block_q: int,
                       block_i: int, precision: str):
    """Build (and cache) the jitted shard_map DBSCAN program for one
    (mesh, shape, block, precision) combination — jit's cache is keyed on
    the function object, so the closure must not be rebuilt per call (same
    discipline as ops.knn._sharded_knn_fn). eps/min_pts are traced
    arguments: a parameter sweep reuses one compiled program."""
    from spark_rapids_ml_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    prec = _dot_precision(precision)

    def local(xq, vq, x_all, v_all, eps_sq, min_pts):
        offset = lax.axis_index(DATA_AXIS) * n_loc

        counts = _eps_neighbor_counts(
            xq, vq, eps_sq, block_q, block_i, prec,
            x_items=x_all, valid_items=v_all,
        )[:n_loc]
        core_loc = (counts >= min_pts) & vq
        core = lax.all_gather(core_loc, DATA_AXIS).reshape(n_tot)

        labels0 = jnp.where(core, jnp.arange(n_tot, dtype=jnp.int32), _INT_MAX)

        def cond(state):
            _, changed = state
            return changed

        def body(state):
            labels, _ = state
            neigh_loc = _min_core_neighbor_label(
                xq, vq, core, labels, eps_sq, block_q, block_i, prec,
                x_items=x_all, valid_items=v_all,
            )
            lab_loc = lax.dynamic_slice(labels, (offset,), (n_loc,))
            new_loc = jnp.where(core_loc, jnp.minimum(lab_loc, neigh_loc), lab_loc)
            new = lax.all_gather(new_loc, DATA_AXIS).reshape(n_tot)
            # Full path compression on the replicated vector (identical on
            # every device, no collective needed): O(log n) sweeps total.
            jumped = _compress_labels(new, core, n_tot)
            return (jumped, jnp.any(jumped != labels))

        labels, _ = lax.while_loop(cond, body, (labels0, jnp.asarray(True)))

        neigh_loc = _min_core_neighbor_label(
            xq, vq, core, labels, eps_sq, block_q, block_i, prec,
            x_items=x_all, valid_items=v_all,
        )
        lab_loc = lax.dynamic_slice(labels, (offset,), (n_loc,))
        border = (~core_loc) & (neigh_loc < _INT_MAX) & vq
        lab_loc = jnp.where(border, neigh_loc, lab_loc)
        lab_loc = jnp.where(lab_loc == _INT_MAX, -1, lab_loc)
        lab_loc = jnp.where(vq, lab_loc, -1)
        labels_out = lax.all_gather(lab_loc, DATA_AXIS).reshape(n_tot)
        return labels_out, core

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(), P()),
        out_specs=(P(), P()),
        # all_gather results are identical on every device; replication
        # holds but the vma checker cannot prove it (as in ops.knn).
        check_vma=False,
    )
    return jax.jit(fn)


def dbscan_labels_sharded(
    mesh,
    x: np.ndarray,
    eps: float,
    min_pts: int,
    block_q: int = 2048,
    block_i: int = 8192,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array]:
    """Mesh DBSCAN: query rows shard over the data axis, the point set is
    replicated (the epsilon sweeps are compute-bound at O(n^2 d); splitting
    the query dimension divides that by the device count while the
    all-gathered label vector — 4n bytes — rides ICI once per diffusion
    round). Returns replicated (labels, core_mask), identical semantics to
    :func:`dbscan_labels`.
    """
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    x = np.asarray(x)
    n, _ = x.shape
    dp = mesh.shape[DATA_AXIS]
    pad = (-n) % dp
    xp = np.pad(x, ((0, pad), (0, 0)))
    validp = np.zeros(n + pad, dtype=bool)
    validp[:n] = True
    n_tot = n + pad
    fn = _sharded_dbscan_fn(mesh, n_tot, n_tot // dp, block_q, block_i, precision)
    xj = jnp.asarray(xp)
    labels, core = fn(
        xj, jnp.asarray(validp), xj, jnp.asarray(validp),
        jnp.asarray(eps, xj.dtype) ** 2, jnp.asarray(min_pts, jnp.int32),
    )
    return labels[:n], core[:n]
