"""Symmetric eigendecomposition + SVD-from-covariance, in XLA.

Replaces the reference's ``calSVD`` JNI export (rapidsml_jni.cu:302-356):
raft::linalg::eigDC (cuSolver syevd) -> colReverse/rowReverse (descending
order) -> seqRoot (sqrt eigenvalues -> singular values) -> deterministic
signFlip (thrust device lambda, rapidsml_jni.cu:37-64).

On TPU, ``jnp.linalg.eigh`` lowers to XLA's self-adjoint eigensolver (a
QDWH/Jacobi family algorithm — the cyclic-Jacobi approach cited in SURVEY.md
§7); the reverse/sqrt/sign-flip postprocessing ops fuse into the same
executable instead of being separate RAFT kernel launches.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def sign_flip(u: jax.Array) -> jax.Array:
    """Deterministic per-column sign convention.

    For each column, if the element with the largest |value| is negative,
    negate the column — exactly the reference's thrust ``signFlip`` device
    lambda (rapidsml_jni.cu:37-64). ``argmax`` ties resolve to the first
    index, matching the sequential scan in the reference's for-loop.
    """
    idx = jnp.argmax(jnp.abs(u), axis=0)
    pivot = u[idx, jnp.arange(u.shape[1])]
    signs = jnp.where(pivot < 0, -1.0, 1.0).astype(u.dtype)
    return u * signs[None, :]


@jax.jit
def eigh_descending(a: jax.Array):
    """Eigendecomposition of symmetric ``a`` with eigenvalues descending.

    Returns ``(eigenvalues, eigenvectors)`` with columns sign-flipped
    deterministically. Covers eigDC + colReverse + rowReverse + signFlip
    (rapidsml_jni.cu:338-343).
    """
    w, v = jnp.linalg.eigh(a)  # ascending
    w = w[::-1]
    v = v[:, ::-1]
    return w, sign_flip(v)


def _sign_flip_host(v):
    """Numpy twin of :func:`sign_flip` — ONE home for the host-side sign
    convention (the reference's signFlip contract)."""
    import numpy as np

    idx = np.argmax(np.abs(v), axis=0)
    pivot = v[idx, np.arange(v.shape[1])]
    return v * np.where(pivot < 0, -1.0, 1.0)[None, :]


def eigh_descending_host(a):
    """Host (NumPy/LAPACK) fallback with the same contract as
    :func:`eigh_descending` — the reference's driver-CPU breeze-SVD branch
    (RapidsRowMatrix.scala:110-123), for callers that opt out of the
    accelerator (``useCuSolverSVD=False``)."""
    import numpy as np

    w, v = np.linalg.eigh(np.asarray(a, dtype=np.float64))
    return w[::-1], _sign_flip_host(v[:, ::-1])


@partial(jax.jit, static_argnames=("k", "iters"))
def eigh_topk(a: jax.Array, k: int, iters: int = 8):
    """Top-k eigenpairs of a symmetric PSD matrix by subspace iteration +
    Rayleigh–Ritz — O(iters · d² · l) MXU matmuls instead of the full
    eigensolver's O(d³) iteration, the right tool when k ≪ d and the
    spectrum decays (PCA's usual regime; ``eigenSolver="topk"``).

    Returns ``(eigenvalues (k,), eigenvectors (d, k))`` descending with the
    deterministic sign flip. Exact explained-variance RATIOS need only
    ``trace(a)``, not the full spectrum, so the caller loses nothing
    there. Deterministic: the start basis comes from a fixed key. For
    near-flat spectra (no decay) the subspace converges but individual
    vectors are as ill-determined as they are for the exact solver.
    """
    d = a.shape[0]
    oversample = min(d, max(2 * k, k + 8))
    q0 = jax.random.normal(jax.random.key(0), (d, oversample), dtype=a.dtype)
    q0, _ = jnp.linalg.qr(q0)
    prec = jax.lax.Precision.HIGHEST

    def body(_, q):
        z = jnp.matmul(a, q, precision=prec)
        q_new, _ = jnp.linalg.qr(z)
        return q_new

    q = jax.lax.fori_loop(0, iters, body, q0)
    # Rayleigh–Ritz on the converged subspace.
    b = jnp.matmul(q.T, jnp.matmul(a, q, precision=prec), precision=prec)
    w, u = jnp.linalg.eigh(b)  # ascending, (l,), (l, l)
    w = w[::-1][:k]
    v = jnp.matmul(q, u[:, ::-1][:, :k], precision=prec)
    return w, sign_flip(v)


def eigh_topk_host(a, k: int):
    """Host fp64 twin of :func:`eigh_topk` for the dd precision path (the
    covariance is exact-fp64 host data there; a device solve would round
    it to fp32). Uses ARPACK (scipy eigsh) with a dense-LAPACK fallback.
    Same contract: descending top-k eigenpairs, deterministic sign flip.
    """
    import numpy as np

    a = np.asarray(a, dtype=np.float64)
    try:
        from scipy.sparse.linalg import eigsh

        w, v = eigsh(a, k=k, which="LA")
        order = np.argsort(w)[::-1]
        w, v = w[order], v[:, order]
    except Exception:  # pragma: no cover - tiny k near d, or no scipy
        w_all, v_all = np.linalg.eigh(a)
        w, v = w_all[::-1][:k], v_all[:, ::-1][:, :k]
    return w, _sign_flip_host(v)


@jax.jit
def cal_svd(a: jax.Array):
    """SVD of a symmetric PSD matrix via eigendecomposition.

    Returns ``(u, s)`` with singular values ``s = sqrt(max(eigenvalues, 0))``
    descending — the reference's full ``calSVD`` contract
    (rapidsml_jni.cu:302-356, seqRoot at :341). Negative eigenvalues (tiny
    numerical noise on a PSD input) clamp to zero rather than produce NaN.
    """
    w, v = eigh_descending(a)
    s = jnp.sqrt(jnp.maximum(w, 0))
    return v, s
