"""Symmetric eigendecomposition + SVD-from-covariance, in XLA.

Replaces the reference's ``calSVD`` JNI export (rapidsml_jni.cu:302-356):
raft::linalg::eigDC (cuSolver syevd) -> colReverse/rowReverse (descending
order) -> seqRoot (sqrt eigenvalues -> singular values) -> deterministic
signFlip (thrust device lambda, rapidsml_jni.cu:37-64).

On TPU, ``jnp.linalg.eigh`` lowers to XLA's self-adjoint eigensolver (a
QDWH/Jacobi family algorithm — the cyclic-Jacobi approach cited in SURVEY.md
§7); the reverse/sqrt/sign-flip postprocessing ops fuse into the same
executable instead of being separate RAFT kernel launches.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def sign_flip(u: jax.Array) -> jax.Array:
    """Deterministic per-column sign convention.

    For each column, if the element with the largest |value| is negative,
    negate the column — exactly the reference's thrust ``signFlip`` device
    lambda (rapidsml_jni.cu:37-64). ``argmax`` ties resolve to the first
    index, matching the sequential scan in the reference's for-loop.
    """
    idx = jnp.argmax(jnp.abs(u), axis=0)
    pivot = u[idx, jnp.arange(u.shape[1])]
    signs = jnp.where(pivot < 0, -1.0, 1.0).astype(u.dtype)
    return u * signs[None, :]


@jax.jit
def eigh_descending(a: jax.Array):
    """Eigendecomposition of symmetric ``a`` with eigenvalues descending.

    Returns ``(eigenvalues, eigenvectors)`` with columns sign-flipped
    deterministically. Covers eigDC + colReverse + rowReverse + signFlip
    (rapidsml_jni.cu:338-343).
    """
    w, v = jnp.linalg.eigh(a)  # ascending
    w = w[::-1]
    v = v[:, ::-1]
    return w, sign_flip(v)


def _sign_flip_host(v):
    """Numpy twin of :func:`sign_flip` — ONE home for the host-side sign
    convention (the reference's signFlip contract)."""
    import numpy as np

    idx = np.argmax(np.abs(v), axis=0)
    pivot = v[idx, np.arange(v.shape[1])]
    return v * np.where(pivot < 0, -1.0, 1.0)[None, :]


def eigh_descending_host(a):
    """Host (NumPy/LAPACK) fallback with the same contract as
    :func:`eigh_descending` — the reference's driver-CPU breeze-SVD branch
    (RapidsRowMatrix.scala:110-123), for callers that opt out of the
    accelerator (``useCuSolverSVD=False``)."""
    import numpy as np

    w, v = np.linalg.eigh(np.asarray(a, dtype=np.float64))
    return w[::-1], _sign_flip_host(v[:, ::-1])


# "auto" treats eigenIters as a CAP on its early-exiting while_loop, with
# this quality floor: fewer than ~12 iterations cannot separate "converged"
# from "degenerate" reliably (ONE home for the floor — RowMatrix call
# sites use auto_max_iters, never a bare max()).
AUTO_MIN_ITERS = 12


def auto_max_iters(eigen_iters: int) -> int:
    return max(int(eigen_iters), AUTO_MIN_ITERS)


def _subspace_l(d: int, k: int) -> int:
    """Oversampled subspace width shared by the iterative solvers."""
    return min(d, max(2 * k, k + 8))


def _start_basis(d: int, l: int, dtype) -> jax.Array:
    """Deterministic orthonormal start basis (fixed key: the fitted model
    must never depend on placement or call order)."""
    q0 = jax.random.normal(jax.random.key(0), (d, l), dtype=dtype)
    q0, _ = jnp.linalg.qr(q0)
    return q0


def _cholqr(z: jax.Array):
    """CholeskyQR re-orthonormalization of a tall-skinny block.

    ``Q = Z · L⁻ᵀ`` with ``LLᵀ = ZᵀZ`` — two MXU matmuls plus an (l, l)
    Cholesky instead of a full Householder QR, which on TPU is the
    dominant cost of a subspace-iteration step (the panel factorization
    is sequential; the Gram/solve here are dense MXU work). A relative
    jitter keeps the Gram PD under fp32 rounding; the resulting loss of
    orthogonality only perturbs the iteration's conditioning, not the
    subspace span, and callers finish with one true QR before
    Rayleigh–Ritz. Returns ``(q, tr(ZᵀZ))`` — the trace is the captured
    second-moment objective the auto solver watches for stagnation.
    """
    l = z.shape[1]
    prec = jax.lax.Precision.HIGHEST
    g = jnp.matmul(z.T, z, precision=prec)
    s = jnp.trace(g)
    eps = 1e-6 if z.dtype == jnp.float32 else 1e-14
    gj = g + (eps * s / l) * jnp.eye(l, dtype=z.dtype)
    lo = jnp.linalg.cholesky(gj)
    linv = jax.scipy.linalg.solve_triangular(
        lo, jnp.eye(l, dtype=z.dtype), lower=True
    )
    return jnp.matmul(z, linv.T, precision=prec), s


def _rayleigh_ritz(a: jax.Array, q: jax.Array, k: int):
    """Final extraction: true QR (exact orthonormality), Rayleigh–Ritz,
    descending top-k with the deterministic sign flip."""
    prec = jax.lax.Precision.HIGHEST
    q, _ = jnp.linalg.qr(q)
    b = jnp.matmul(q.T, jnp.matmul(a, q, precision=prec), precision=prec)
    w, u = jnp.linalg.eigh(b)  # ascending, (l,), (l, l)
    w = w[::-1][:k]
    v = jnp.matmul(q, u[:, ::-1][:, :k], precision=prec)
    return w, sign_flip(v)


@partial(jax.jit, static_argnames=("k", "iters"))
def eigh_topk(a: jax.Array, k: int, iters: int = 8):
    """Top-k eigenpairs of a symmetric PSD matrix by subspace iteration +
    Rayleigh–Ritz — O(iters · d² · l) MXU matmuls instead of the full
    eigensolver's O(d³) iteration, the right tool when k ≪ d and the
    spectrum decays (PCA's usual regime; ``eigenSolver="topk"``).

    Returns ``(eigenvalues (k,), eigenvectors (d, k))`` descending with the
    deterministic sign flip. Exact explained-variance RATIOS need only
    ``trace(a)``, not the full spectrum, so the caller loses nothing
    there. Deterministic: the start basis comes from a fixed key. For
    near-flat spectra (no decay) the subspace converges but individual
    vectors are as ill-determined as they are for the exact solver.
    Inner steps re-orthonormalize with CholeskyQR (:func:`_cholqr`) and a
    single true QR precedes the final Rayleigh–Ritz.
    """
    d = a.shape[0]
    l = _subspace_l(d, k)
    q0 = _start_basis(d, l, a.dtype)
    prec = jax.lax.Precision.HIGHEST

    def body(_, q):
        z = jnp.matmul(a, q, precision=prec)
        q_new, _ = _cholqr(z)
        return q_new

    q = jax.lax.fori_loop(0, iters, body, q0)
    return _rayleigh_ritz(a, q, k)


@partial(jax.jit, static_argnames=("k", "max_iters", "cluster_tol"))
def eigh_auto(a: jax.Array, k: int, max_iters: int = 16, cluster_tol: float = 0.05):
    """Self-selecting top-k eigensolver (``eigenSolver="auto"``): subspace
    iteration with a runtime acceptance check that PROMOTES itself to the
    full eigensolver when the spectrum defeats it — the check VERDICT r2
    asked for, replacing the static full-vs-topk choice.

    Decision rule (all on device, one ``lax.while_loop`` + one
    ``lax.cond``):
      - iterate ``Z = A·Q`` + CholeskyQR, exiting early when the captured
        second-moment objective ``s = tr(QᵀA²Q)`` (free — the trace of the
        CholeskyQR Gram) stagnates: converged spectra stop in a handful of
        steps; slow/degenerate spectra run to ``max_iters``.
      - Rayleigh–Ritz extract over the full l-wide band, then ACCEPT iff
        every kept pair is either
        (a) CONVERGED: ``residᵢ = ‖A·vᵢ − wᵢ·vᵢ‖ ≤ vec_tol·wᵢ`` — a true
        eigenpair to working precision, or
        (b) DEGENERATE: its local Ritz spacing is below its residual
        (``min gap to neighboring Ritz values ≤ residᵢ``) AND
        ``residᵢ ≤ cluster_tol·wᵢ``. By the Davis–Kahan/residual bound
        such a pair mixes only among eigen-directions whose eigenvalues
        lie within ``residᵢ`` of ``wᵢ`` — and the spacing test certifies
        the spectrum is genuinely unresolved at that resolution, where
        the exact solver's vectors are equally arbitrary basis choices
        inside the cluster. Eigenvalues (hence explained-variance ratios)
        stay correct to ``cluster_tol`` relative either way.
        A spectrum with REAL gaps at the residual scale (resolvable but
        unconverged — slow decay) fails both arms and falls through to
        ``eigh_descending`` (the promoted branch executes only when
        taken — ``lax.cond``).

    Returns ``(w (k,), v (d, k), promoted)`` descending, sign-flipped;
    ``promoted`` reports which solver produced the result. The acceptance
    thresholds are validated by an adversarial spectrum sweep in
    ``tests/test_device_input.py`` (geometric ratios, steps, clusters,
    Marchenko–Pastur noise).
    """
    d = a.shape[0]
    if k >= d:  # no subspace to iterate — the full solve IS the answer
        w, v = eigh_descending(a)
        return w[:k], v[:, :k], jnp.asarray(True)
    l = _subspace_l(d, k)
    q0 = _start_basis(d, l, a.dtype)
    prec = jax.lax.Precision.HIGHEST
    f32 = a.dtype == jnp.float32
    stag_tol = 1e-5 if f32 else 1e-11
    vec_tol = 1e-3 if f32 else 1e-8
    eps_abs = 1e-5 if f32 else 1e-12

    def cond_fn(state):
        i, _, _, stagnated = state
        return jnp.logical_and(i < max_iters, jnp.logical_not(stagnated))

    def body_fn(state):
        i, q, s_prev, _ = state
        z = jnp.matmul(a, q, precision=prec)
        q_new, s = _cholqr(z)
        stagnated = jnp.abs(s - s_prev) <= stag_tol * s
        return i + 1, q_new, s, stagnated

    neg = jnp.asarray(-jnp.inf, dtype=a.dtype)
    _, q, _, _ = jax.lax.while_loop(
        cond_fn, body_fn, (0, q0, neg, jnp.asarray(False))
    )
    # Inline Rayleigh–Ritz keeping ALL l Ritz values: the acceptance test
    # needs the kept components' neighbors to measure local spacing.
    q, _ = jnp.linalg.qr(q)
    b = jnp.matmul(q.T, jnp.matmul(a, q, precision=prec), precision=prec)
    w_all, u = jnp.linalg.eigh(b)  # ascending
    w_all = w_all[::-1]  # (l,) descending
    w_k = w_all[:k]
    v_k = sign_flip(jnp.matmul(q, u[:, ::-1][:, :k], precision=prec))
    r = jnp.matmul(a, v_k, precision=prec) - v_k * w_k[None, :]
    resid = jnp.linalg.norm(r, axis=0)
    scale = eps_abs * w_all[0]
    # Local Ritz spacing of each kept component (right neighbor always
    # exists: l >= k+1 here since k < d and l > k by construction).
    gap_right = w_k - w_all[1 : k + 1]
    gap_left = jnp.concatenate(
        [jnp.full((1,), jnp.inf, dtype=w_all.dtype), w_all[: k - 1] - w_k[1:]]
    ) if k > 1 else jnp.full((1,), jnp.inf, dtype=w_all.dtype)
    spacing = jnp.minimum(gap_left, gap_right)
    converged = resid <= vec_tol * w_k + scale
    degenerate = jnp.logical_and(
        spacing <= resid, resid <= cluster_tol * w_k + scale
    )
    accept = jnp.all(jnp.logical_or(converged, degenerate))

    def keep(_):
        return w_k, v_k

    def promote(_):
        w, v = eigh_descending(a)
        return w[:k], v[:, :k]

    w, v = jax.lax.cond(accept, keep, promote, None)
    return w, v, jnp.logical_not(accept)


def eigh_topk_host(a, k: int):
    """Host fp64 twin of :func:`eigh_topk` for the dd precision path (the
    covariance is exact-fp64 host data there; a device solve would round
    it to fp32). Uses ARPACK (scipy eigsh) with a dense-LAPACK fallback.
    Same contract: descending top-k eigenpairs, deterministic sign flip.
    """
    import numpy as np

    a = np.asarray(a, dtype=np.float64)
    try:
        from scipy.sparse.linalg import eigsh

        w, v = eigsh(a, k=k, which="LA")
        order = np.argsort(w)[::-1]
        w, v = w[order], v[:, order]
    except Exception:  # pragma: no cover - tiny k near d, or no scipy
        w_all, v_all = np.linalg.eigh(a)
        w, v = w_all[::-1][:k], v_all[:, ::-1][:, :k]
    return w, _sign_flip_host(v)


@jax.jit
def cal_svd(a: jax.Array):
    """SVD of a symmetric PSD matrix via eigendecomposition.

    Returns ``(u, s)`` with singular values ``s = sqrt(max(eigenvalues, 0))``
    descending — the reference's full ``calSVD`` contract
    (rapidsml_jni.cu:302-356, seqRoot at :341). Negative eigenvalues (tiny
    numerical noise on a PSD input) clamp to zero rather than produce NaN.
    """
    w, v = eigh_descending(a)
    s = jnp.sqrt(jnp.maximum(w, 0))
    return v, s
