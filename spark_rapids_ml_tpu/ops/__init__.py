"""Accelerated kernels as XLA computations.

This package replaces the reference's entire native layer
(native/src/rapidsml_jni.cu: cublasDspr / cublasDgemm / raft eigDC+signFlip)
with jitted JAX/XLA functions. Per-call cudaMalloc/memcpy disappears: jit
compiles once per shape and XLA manages HBM buffers.
"""

from spark_rapids_ml_tpu.ops.linalg import gemm_syrk, gemm_project, spr, triu_to_full
from spark_rapids_ml_tpu.ops.eigh import eigh_descending, sign_flip, cal_svd
from spark_rapids_ml_tpu.ops.covariance import covariance, mean_and_covariance

__all__ = [
    "gemm_syrk",
    "gemm_project",
    "spr",
    "triu_to_full",
    "eigh_descending",
    "sign_flip",
    "cal_svd",
    "covariance",
    "mean_and_covariance",
]
