"""Approximate nearest neighbors — IVF-Flat and IVF-PQ, redesigned for the MXU.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2; the modern RAPIDS Spark-ML line grew ApproximateNearestNeighbors on
cuML, algorithms ``ivfflat`` and ``ivfpq``). cuML's IVF walks per-list
inverted indices with variable-length lists and warp-level scans — dynamic
shapes and pointer-chasing a TPU can't tile. TPU-first redesign:

  - **Coarse quantizer**: k-means over the items (``ops.kmeans`` — GEMM
    Lloyd on the MXU).
  - **Inverted lists as one dense tensor**: items grouped by list into a
    (n_lists, L_max, d) array padded to the longest list, with a parallel
    mask and original-index tensor. Padding trades HBM for static shapes —
    the XLA-friendly version of CSR lists.
  - **Search**: one (Bq, d) x (d, n_lists) GEMM ranks centroids, then a
    ``lax.scan`` over the ``n_probe`` chosen lists: gather the (Bq, L_max)
    candidate block, batched distance via einsum (MXU), and a running
    top-k merge — identical merge discipline to ``ops.knn``. Live memory
    is O(Bq * L_max * d), independent of n_probe and the item count.

Setting ``n_probe = n_lists`` makes the search exact (every list probed),
which the tests exploit as a brute-force oracle.

**IVF-PQ** adds product quantization of the per-list residuals: the feature
axis splits into M subspaces, each residual subvector is snapped to one of
2^n_bits codebook entries (codebooks trained by the same GEMM Lloyd,
vmapped over subspaces), and search replaces the per-item distance GEMM
with an ADC lookup — a (Bq, M, K) distance table per probed list (one small
batched GEMM) followed by M table gathers summed over subspaces. Memory per
item drops from 4·d bytes to M code bytes; the table gather is the TPU
analogue of cuML's shared-memory LUT walk.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_ml_tpu.ops.kmeans import assign_clusters, kmeans_plusplus_init, lloyd
from spark_rapids_ml_tpu.ops.linalg import _dot_precision


class IVFIndex(NamedTuple):
    """Dense IVF-Flat index. All arrays are device-placeable.

    centroids: (n_lists, d)
    lists:     (n_lists, L_max, d)  — items grouped by nearest centroid
    list_mask: (n_lists, L_max)     — 1.0 real row / 0.0 padding
    list_ids:  (n_lists, L_max)     — original item indices, -1 at padding
    """

    centroids: jax.Array
    lists: jax.Array
    list_mask: jax.Array
    list_ids: jax.Array

    @property
    def n_lists(self) -> int:
        return self.lists.shape[0]


def _coarse_quantizer(items: np.ndarray, n_lists: int, seed: int,
                      kmeans_iters: int, mesh=None):
    """k-means++ + Lloyd over the items; with a mesh the rows shard over
    the data axis and the per-iteration stats merge through GSPMD-inserted
    psums (the same sharded Lloyd the KMeans estimator uses). Returns
    (centroids (n_lists, d), labels (n,)) as host arrays."""
    n, d = items.shape
    key = jax.random.key(seed)
    if mesh is None:
        x = jnp.asarray(items)
        mask = jnp.ones(n, dtype=x.dtype)
        data_shards = 1
    else:
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, shard_rows

        x, mask, _ = shard_rows(items, mesh)
        data_shards = mesh.shape[DATA_AXIS]
    init = kmeans_plusplus_init(x, mask, key, n_lists)
    centroids, _, _ = lloyd(
        x, mask, init, max_iter=kmeans_iters, tol=1e-4, data_shards=data_shards
    )
    if 4 * n * n_lists > 2_000_000_000:
        # The full (n, n_lists) assignment matrix would blow HBM at
        # beyond-HBM-benchmark scales — block the final assignment.
        from spark_rapids_ml_tpu.ops.kmeans import assign_clusters_blocked

        labels, _ = assign_clusters_blocked(x, centroids)
    else:
        labels, _ = assign_clusters(x, centroids)
    # Strip row padding (mesh) and model-axis feature padding.
    return np.asarray(centroids)[:, :d], np.asarray(labels)[:n]


def build_ivf_index(
    items: np.ndarray,
    n_lists: int,
    seed: int = 0,
    kmeans_iters: int = 10,
    mesh=None,
) -> IVFIndex:
    """Train the coarse quantizer and pack the inverted lists.

    The quantizer runs on device (k-means++ init + Lloyd — mesh-sharded
    over the data axis when ``mesh`` is given, closing VERDICT r1 missing
    item 6); the group-by-list packing is a host-side argsort (one pass,
    done once at fit time).
    """
    items = np.asarray(items)
    n, d = items.shape
    if not 1 <= n_lists <= n:
        raise ValueError(f"n_lists must be in [1, {n}], got {n_lists}")

    centroids, labels = _coarse_quantizer(items, n_lists, seed, kmeans_iters, mesh)

    order = np.argsort(labels, kind="stable")
    counts = np.bincount(labels, minlength=n_lists)
    l_max = max(int(counts.max()), 1)

    lists = np.zeros((n_lists, l_max, d), dtype=items.dtype)
    list_mask = np.zeros((n_lists, l_max), dtype=items.dtype)
    list_ids = np.full((n_lists, l_max), -1, dtype=np.int32)
    starts = np.concatenate([[0], np.cumsum(counts)])
    for lid in range(n_lists):
        sel = order[starts[lid] : starts[lid + 1]]
        lists[lid, : sel.size] = items[sel]
        list_mask[lid, : sel.size] = 1.0
        list_ids[lid, : sel.size] = sel

    return IVFIndex(
        centroids=jnp.asarray(centroids),
        lists=jnp.asarray(lists),
        list_mask=jnp.asarray(list_mask),
        list_ids=jnp.asarray(list_ids),
    )


def _probe_scaffold(index, queries, k, n_probe, block_q, prec, list_d2_fn):
    """Shared IVF search scaffold: query blocking/padding, coarse centroid
    ranking, scan over probed lists with a running top-k merge.

    ``list_d2_fn(qb, q_sq, lid)`` computes the (Bq, L_max) squared-distance
    estimate of query block ``qb`` against list ``lid`` — the ONLY piece
    that differs between IVF-Flat (exact GEMM) and IVF-PQ (ADC tables).
    Unfilled slots surface as (inf, -1).
    """
    n_lists = index.list_mask.shape[0]
    if not 1 <= n_probe <= n_lists:
        raise ValueError(f"n_probe must be in [1, {n_lists}], got {n_probe}")
    nq, d = queries.shape
    dtype = queries.dtype

    n_qblocks = -(-nq // block_q)
    pad = n_qblocks * block_q - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))

    def one_query_block(qb):
        q_sq = jnp.sum(qb * qb, axis=1)
        c_sq = jnp.sum(index.centroids * index.centroids, axis=1)
        qc = jnp.matmul(qb, index.centroids.T, precision=prec)
        cd2 = q_sq[:, None] - 2.0 * qc + c_sq[None, :]
        _, probe_ids = lax.top_k(-cd2, n_probe)  # (Bq, n_probe)

        init = (
            jnp.full((block_q, k), jnp.inf, dtype=dtype),
            jnp.full((block_q, k), -1, jnp.int32),
        )

        def probe_step(carry, p):
            best_d, best_i = carry
            lid = probe_ids[:, p]  # (Bq,)
            d2 = list_d2_fn(qb, q_sq, lid)
            d2 = jnp.where(index.list_mask[lid] > 0, d2, jnp.inf)
            cand_d = jnp.concatenate([best_d, d2], axis=1)
            cand_i = jnp.concatenate([best_i, index.list_ids[lid]], axis=1)
            neg_top, pos = lax.top_k(-cand_d, k)
            return (-neg_top, jnp.take_along_axis(cand_i, pos, axis=1)), None

        (best_d, best_i), _ = lax.scan(
            probe_step, init, jnp.arange(n_probe, dtype=jnp.int32)
        )
        return best_d, best_i

    qblocks = qp.reshape(n_qblocks, block_q, d)
    best_d, best_i = lax.map(one_query_block, qblocks)
    return (
        best_d.reshape(n_qblocks * block_q, k)[:nq],
        best_i.reshape(n_qblocks * block_q, k)[:nq],
    )


@partial(jax.jit, static_argnames=("k", "n_probe", "block_q", "precision"))
def ivf_search(
    index: IVFIndex,
    queries: jax.Array,
    k: int,
    n_probe: int,
    block_q: int = 1024,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array]:
    """Top-k approximate neighbors: (sq-distances (nq, k), indices (nq, k)).

    Indices are original item indices; unfilled slots (fewer than k
    candidates in the probed lists) are (inf, -1).
    """
    prec = _dot_precision(precision)
    item_sq = jnp.sum(index.lists * index.lists, axis=2)  # (n_lists, L_max)

    def list_d2(qb, q_sq, lid):
        xb = index.lists[lid]  # (Bq, L_max, d) gather
        cross = jnp.einsum("bd,bld->bl", qb, xb, precision=prec)
        return jnp.maximum(q_sq[:, None] - 2.0 * cross + item_sq[lid], 0.0)

    return _probe_scaffold(index, queries, k, n_probe, block_q, prec, list_d2)


class IVFPQIndex(NamedTuple):
    """Dense IVF-PQ index: coarse lists + per-subspace residual codebooks.

    centroids: (n_lists, d)
    codebooks: (M, K, ds)        — K = 2^n_bits entries per subspace
    codes:     (n_lists, L_max, M) int32 — residual code per item/subspace
    list_mask: (n_lists, L_max)
    list_ids:  (n_lists, L_max)  — original item indices, -1 at padding
    """

    centroids: jax.Array
    codebooks: jax.Array
    codes: jax.Array
    list_mask: jax.Array
    list_ids: jax.Array

    @property
    def n_lists(self) -> int:
        return self.codes.shape[0]


def build_ivfpq_index(
    items: np.ndarray,
    n_lists: int,
    m_subspaces: int,
    n_bits: int = 8,
    seed: int = 0,
    kmeans_iters: int = 10,
    pq_iters: int = 10,
    mesh=None,
) -> IVFPQIndex:
    """Train the coarse quantizer, then per-subspace residual codebooks.

    Builds on the IVF-Flat packer for grouping; the PQ training runs one
    GEMM Lloyd per subspace over the residuals — with a mesh, both the
    coarse quantizer AND each codebook Lloyd shard their rows over the
    data axis (VERDICT r1 missing item 6).
    """
    items = np.asarray(items)
    n, d = items.shape
    if d % m_subspaces != 0:
        raise ValueError(f"d={d} not divisible by M={m_subspaces} subspaces")
    if not 1 <= n_bits <= 8:
        raise ValueError(f"n_bits must be in [1, 8], got {n_bits}")
    ds = d // m_subspaces
    n_codes = min(1 << n_bits, n)

    flat = build_ivf_index(
        items, n_lists, seed=seed, kmeans_iters=kmeans_iters, mesh=mesh
    )
    # Residuals of the REAL items, flattened over lists (padding excluded
    # from training via its zero mask weight).
    residuals = flat.lists - flat.centroids[:, None, :]  # (n_lists, L_max, d)
    r = residuals.reshape(-1, d)
    w = flat.list_mask.reshape(-1)

    if mesh is not None:
        from spark_rapids_ml_tpu.parallel.mesh import (
            DATA_AXIS,
            shard_rows,
            weights_as_mask,
        )

        data_shards = mesh.shape[DATA_AXIS]
        # Shard the FULL residual matrix once; per-subspace training
        # slices its columns device-side (no per-subspace host round-trip
        # or mask rebuild — all M Lloyds reuse the same placement).
        r_s, _, _ = shard_rows(np.asarray(r), mesh)
        w_s = weights_as_mask(np.asarray(w), r_s.shape[0], r_s.dtype, mesh)
    else:
        data_shards = 1

    key = jax.random.key(seed + 1)
    codebooks = []
    codes = []
    r_sub = r.reshape(r.shape[0], m_subspaces, ds)
    for m in range(m_subspaces):
        rm = r_sub[:, m, :]
        if mesh is not None:
            rm_s = r_s[:, m * ds : (m + 1) * ds]
            init = kmeans_plusplus_init(rm_s, w_s, jax.random.fold_in(key, m), n_codes)
            cb, _, _ = lloyd(
                rm_s, w_s, init, max_iter=pq_iters, tol=1e-4,
                data_shards=data_shards,
            )
        else:
            init = kmeans_plusplus_init(rm, w, jax.random.fold_in(key, m), n_codes)
            cb, _, _ = lloyd(rm, w, init, max_iter=pq_iters, tol=1e-4)
        code_m, _ = assign_clusters(rm, jnp.asarray(cb))
        codebooks.append(jnp.asarray(cb))
        codes.append(code_m)
    codebooks = jnp.stack(codebooks)  # (M, K, ds)
    # uint8 delivers the documented M-bytes-per-item footprint (n_bits <= 8
    # guarantees codes fit); search upcasts per probed block for indexing.
    codes = jnp.stack(codes, axis=-1).reshape(
        flat.lists.shape[0], flat.lists.shape[1], m_subspaces
    ).astype(jnp.uint8)

    return IVFPQIndex(
        centroids=flat.centroids,
        codebooks=codebooks,
        codes=codes,
        list_mask=flat.list_mask,
        list_ids=flat.list_ids,
    )


@partial(jax.jit, static_argnames=("k", "n_probe", "block_q", "precision"))
def ivfpq_search(
    index: IVFPQIndex,
    queries: jax.Array,
    k: int,
    n_probe: int,
    block_q: int = 1024,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array]:
    """Top-k by ADC (asymmetric distance): (sq-distances (nq, k), ids (nq, k)).

    Per probed list: residual r = q - centroid, one batched GEMM builds the
    (Bq, M, K) subspace distance table, then d2(item) = sum_m LUT[m, code_m]
    via M gathers. Distances are quantization approximations of the true
    squared euclidean distance (standard IVF-PQ semantics).
    """
    n_lists, l_max, m_sub = index.codes.shape
    _, n_codes, ds = index.codebooks.shape
    prec = _dot_precision(precision)
    cb_sq = jnp.sum(index.codebooks * index.codebooks, axis=2)  # (M, K)

    def list_d2(qb, q_sq, lid):
        bq = qb.shape[0]
        r = (qb - index.centroids[lid]).reshape(bq, m_sub, ds)
        # ADC table: ||r_m - cb[m, j]||^2 for every subspace/entry.
        r_sq = jnp.sum(r * r, axis=2)  # (Bq, M)
        cross = jnp.einsum(
            "bms,mjs->bmj", r, index.codebooks, precision=prec
        )  # (Bq, M, K)
        lut = jnp.maximum(r_sq[:, :, None] - 2.0 * cross + cb_sq[None, :, :], 0.0)
        codes_b = index.codes[lid].astype(jnp.int32)  # (Bq, L_max, M)
        rows = jnp.arange(bq)[:, None]
        d2 = jnp.zeros((bq, l_max), dtype=qb.dtype)
        for m in range(m_sub):  # static M: unrolled table gathers
            d2 = d2 + lut[:, m, :][rows, codes_b[:, :, m]]
        return d2

    return _probe_scaffold(index, queries, k, n_probe, block_q, prec, list_d2)


def dispatch_search(index):
    """The one home of the index-type -> search-kernel dispatch."""
    return ivfpq_search if isinstance(index, IVFPQIndex) else ivf_search


@functools.lru_cache(maxsize=None)
def _sharded_ann_fn(mesh, is_pq: bool, n_fields: int, k: int, n_probe: int,
                    block_q: int, precision: str):
    """Build (and cache) the jitted shard_map search for one configuration —
    jit's cache is keyed on the function object, so the closure must not be
    rebuilt per call (same discipline as ops.knn._sharded_knn_fn)."""
    from spark_rapids_ml_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    search = ivfpq_search if is_pq else ivf_search
    index_cls = IVFPQIndex if is_pq else IVFIndex

    def local(q, *fields):
        return search(
            index_cls(*fields), q, k=k, n_probe=n_probe, block_q=block_q,
            precision=precision,
        )

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS),) + (P(),) * n_fields,
        out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(fn)


def ann_search_sharded(
    mesh,
    index,
    queries: jax.Array,
    k: int,
    n_probe: int,
    block_q: int = 1024,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array]:
    """Mesh ANN search: QUERIES shard over the data axis, the index is
    replicated — each device probes its query shard independently (per-query
    results need no cross-device merge), dividing search compute by the
    device count. Works for both IVF-Flat and IVF-PQ indexes.

    (The complementary layout — lists sharded, queries replicated — would
    divide index MEMORY instead but leave every device doing the full probe
    compute; query sharding is the right default for the search-throughput
    regime the estimator serves.)
    """
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    dp = mesh.shape[DATA_AXIS]
    nq = queries.shape[0]
    pad = (-nq) % dp
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    fn = _sharded_ann_fn(
        mesh, isinstance(index, IVFPQIndex), len(index), k, n_probe, block_q,
        precision,
    )
    d2, ids = fn(qp, *index)
    return d2[:nq], ids[:nq]
