"""Approximate nearest neighbors — IVF-Flat, redesigned for the MXU.

Beyond-the-reference capability (the reference ships only PCA — SURVEY.md
§2; the modern RAPIDS Spark-ML line grew ApproximateNearestNeighbors on
cuML, default algorithm ``ivfflat``). cuML's IVF-Flat walks per-list
inverted indices with variable-length lists and warp-level scans — dynamic
shapes and pointer-chasing a TPU can't tile. TPU-first redesign:

  - **Coarse quantizer**: k-means over the items (``ops.kmeans`` — GEMM
    Lloyd on the MXU).
  - **Inverted lists as one dense tensor**: items grouped by list into a
    (n_lists, L_max, d) array padded to the longest list, with a parallel
    mask and original-index tensor. Padding trades HBM for static shapes —
    the XLA-friendly version of CSR lists.
  - **Search**: one (Bq, d) x (d, n_lists) GEMM ranks centroids, then a
    ``lax.scan`` over the ``n_probe`` chosen lists: gather the (Bq, L_max)
    candidate block, batched distance via einsum (MXU), and a running
    top-k merge — identical merge discipline to ``ops.knn``. Live memory
    is O(Bq * L_max * d), independent of n_probe and the item count.

Setting ``n_probe = n_lists`` makes the search exact (every list probed),
which the tests exploit as a brute-force oracle.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_ml_tpu.ops.kmeans import assign_clusters, kmeans_plusplus_init, lloyd
from spark_rapids_ml_tpu.ops.linalg import _dot_precision


class IVFIndex(NamedTuple):
    """Dense IVF-Flat index. All arrays are device-placeable.

    centroids: (n_lists, d)
    lists:     (n_lists, L_max, d)  — items grouped by nearest centroid
    list_mask: (n_lists, L_max)     — 1.0 real row / 0.0 padding
    list_ids:  (n_lists, L_max)     — original item indices, -1 at padding
    """

    centroids: jax.Array
    lists: jax.Array
    list_mask: jax.Array
    list_ids: jax.Array

    @property
    def n_lists(self) -> int:
        return self.lists.shape[0]


def build_ivf_index(
    items: np.ndarray,
    n_lists: int,
    seed: int = 0,
    kmeans_iters: int = 10,
) -> IVFIndex:
    """Train the coarse quantizer and pack the inverted lists.

    The quantizer runs on device (k-means++ init + Lloyd); the group-by-list
    packing is a host-side argsort (one pass, done once at fit time).
    """
    items = np.asarray(items)
    n, d = items.shape
    if not 1 <= n_lists <= n:
        raise ValueError(f"n_lists must be in [1, {n}], got {n_lists}")

    x = jnp.asarray(items)
    mask = jnp.ones(n, dtype=x.dtype)
    key = jax.random.key(seed)
    init = kmeans_plusplus_init(x, mask, key, n_lists)
    centroids, _, _ = lloyd(x, mask, init, max_iter=kmeans_iters, tol=1e-4)
    labels, _ = assign_clusters(x, centroids)
    labels = np.asarray(labels)

    order = np.argsort(labels, kind="stable")
    counts = np.bincount(labels, minlength=n_lists)
    l_max = max(int(counts.max()), 1)

    lists = np.zeros((n_lists, l_max, d), dtype=items.dtype)
    list_mask = np.zeros((n_lists, l_max), dtype=items.dtype)
    list_ids = np.full((n_lists, l_max), -1, dtype=np.int32)
    starts = np.concatenate([[0], np.cumsum(counts)])
    for lid in range(n_lists):
        sel = order[starts[lid] : starts[lid + 1]]
        lists[lid, : sel.size] = items[sel]
        list_mask[lid, : sel.size] = 1.0
        list_ids[lid, : sel.size] = sel

    return IVFIndex(
        centroids=jnp.asarray(np.asarray(centroids)),
        lists=jnp.asarray(lists),
        list_mask=jnp.asarray(list_mask),
        list_ids=jnp.asarray(list_ids),
    )


@partial(jax.jit, static_argnames=("k", "n_probe", "block_q", "precision"))
def ivf_search(
    index: IVFIndex,
    queries: jax.Array,
    k: int,
    n_probe: int,
    block_q: int = 1024,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array]:
    """Top-k approximate neighbors: (sq-distances (nq, k), indices (nq, k)).

    Indices are original item indices; unfilled slots (fewer than k
    candidates in the probed lists) are (inf, -1).
    """
    n_lists, l_max, d = index.lists.shape
    if not 1 <= n_probe <= n_lists:
        raise ValueError(f"n_probe must be in [1, {n_lists}], got {n_probe}")
    prec = _dot_precision(precision)
    nq = queries.shape[0]
    dtype = queries.dtype

    n_qblocks = -(-nq // block_q)
    pad = n_qblocks * block_q - nq
    qp = jnp.pad(queries, ((0, pad), (0, 0)))

    item_sq = jnp.sum(index.lists * index.lists, axis=2)  # (n_lists, L_max)

    def one_query_block(qb):
        q_sq = jnp.sum(qb * qb, axis=1)
        c_sq = jnp.sum(index.centroids * index.centroids, axis=1)
        qc = jnp.matmul(qb, index.centroids.T, precision=prec)
        cd2 = q_sq[:, None] - 2.0 * qc + c_sq[None, :]
        _, probe_ids = lax.top_k(-cd2, n_probe)  # (Bq, n_probe)

        init = (
            jnp.full((block_q, k), jnp.inf, dtype=dtype),
            jnp.full((block_q, k), -1, jnp.int32),
        )

        def probe_step(carry, p):
            best_d, best_i = carry
            lid = probe_ids[:, p]  # (Bq,)
            xb = index.lists[lid]  # (Bq, L_max, d) gather
            mb = index.list_mask[lid]
            ib = index.list_ids[lid]
            xb_sq = item_sq[lid]
            cross = jnp.einsum("bd,bld->bl", qb, xb, precision=prec)
            d2 = jnp.maximum(q_sq[:, None] - 2.0 * cross + xb_sq, 0.0)
            d2 = jnp.where(mb > 0, d2, jnp.inf)
            cand_d = jnp.concatenate([best_d, d2], axis=1)
            cand_i = jnp.concatenate([best_i, ib], axis=1)
            neg_top, pos = lax.top_k(-cand_d, k)
            return (-neg_top, jnp.take_along_axis(cand_i, pos, axis=1)), None

        (best_d, best_i), _ = lax.scan(
            probe_step, init, jnp.arange(n_probe, dtype=jnp.int32)
        )
        return best_d, best_i

    qblocks = qp.reshape(n_qblocks, block_q, d)
    best_d, best_i = lax.map(one_query_block, qblocks)
    return (
        best_d.reshape(n_qblocks * block_q, k)[:nq],
        best_i.reshape(n_qblocks * block_q, k)[:nq],
    )
