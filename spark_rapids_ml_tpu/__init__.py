"""spark_rapids_ml_tpu — a TPU-native accelerator for Spark-ML-style estimators.

Built from scratch with the capabilities of the CUDA-based reference
(pxLi/spark-rapids-ml): drop-in estimators whose numeric kernels run on TPU
via JAX/XLA instead of cuBLAS/cuSolver via JNI.

Layer map (mirrors SURVEY.md §1 of the reference):
  - ``feature`` / ``models``  — user-facing estimators (reference L1/L2:
    com.nvidia.spark.ml.feature.PCA / RapidsPCA, RapidsPCA.scala)
  - ``linalg``                — distributed linear algebra orchestration
    (reference L3: RapidsRowMatrix.scala)
  - ``ops``                   — the accelerated kernels as XLA computations
    (reference L4-L6: RAPIDSML.scala -> JniRAPIDSML.java -> rapidsml_jni.cu)
  - ``parallel``              — device-mesh sharding + collectives (the
    reference delegates this to Spark RDD reduce/broadcast)
  - ``utils.tracing``         — profiling ranges (reference L7: NvtxRange)
  - ``robustness``            — fault injection + retry/degradation policy
    (the reference delegated its whole failure story to Spark task retry)
  - ``native``                — C++ host runtime (reference: native/ JNI lib)
"""

from spark_rapids_ml_tpu.version import __version__

__all__ = ["__version__"]
