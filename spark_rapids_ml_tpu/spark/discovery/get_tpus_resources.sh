#!/usr/bin/env bash
# TPU resource discovery script for Spark executors — the getTpusResources
# analogue of the reference's getGpusResources.sh (README.md:83-86 wiring:
#   spark.executor.resource.tpu.discoveryScript=this file
#   spark.executor.resource.tpu.amount=<chips per executor, normally 1>
#   spark.task.resource.tpu.amount=1
# ). TPU chips are single-tenant: unlike the reference's fractional
# gpu.amount=0.08 oversubscription (12 tasks sharing one GPU), one task owns
# one chip and parallelism comes from partition count (SURVEY.md §7 hard
# part #4).
#
# Prints the Spark ResourceInformation JSON: {"name": "tpu", "addresses": [...]}.
set -euo pipefail

# Preferred: ask the accelerator runtime. Works on Cloud TPU VMs where the
# libtpu device nodes are /dev/accel* (one per chip), and in environments
# exposing TPU_CHIPS_PER_HOST_BOUNDS / TPU_VISIBLE_DEVICES.
addresses=()

if [[ -n "${TPU_VISIBLE_DEVICES:-}" ]]; then
  IFS=',' read -r -a addresses <<< "${TPU_VISIBLE_DEVICES}"
elif compgen -G "/dev/accel*" > /dev/null; then
  for dev in /dev/accel*; do
    addresses+=("${dev#/dev/accel}")
  done
elif command -v python3 > /dev/null; then
  # Fallback: enumerate via JAX (slow path; only at executor bring-up).
  mapfile -t addresses < <(python3 - <<'PY' 2>/dev/null || true
import jax
for d in jax.devices():
    if d.platform in ("tpu", "axon"):
        print(d.id)
PY
)
fi

if [[ ${#addresses[@]} -eq 0 ]]; then
  echo '{"name": "tpu", "addresses": []}'
  exit 0
fi

printf '{"name": "tpu", "addresses": ['
for i in "${!addresses[@]}"; do
  [[ $i -gt 0 ]] && printf ','
  printf '"%s"' "${addresses[$i]}"
done
printf ']}\n'
