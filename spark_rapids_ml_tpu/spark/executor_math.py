"""Pure-numpy model forwards for Spark EXECUTOR processes.

The adapter's contract is that executors need numpy only — no JAX, no
chip (adapter.py module docstring; the reference's executors likewise run
JVM+CUDA-lib only, no Spark-side ML framework). Transform pandas_udfs
therefore close over plain numpy parameter arrays plus the functions in
THIS module (imported by reference on the executor, pulling in nothing but
numpy) — never over core model objects, whose modules import jax at the
top level.

The math mirrors the core kernels exactly: ``logistic_forward`` twins
ops/logistic.predict_logistic (raw = [-z, z] margins for binomial, logits
for multinomial); ``forest_forward`` twins ops/trees.forest_apply +
forest_predict_proba (heap-indexed routing, LEFT when x[feature] <=
threshold, probs = mean leaf distribution, raw = vote mass).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def logistic_forward(
    weights: np.ndarray,  # (d, 1) binomial or (d, C) multinomial
    intercepts: np.ndarray,  # (1,) or (C,)
    threshold: float,
    block: np.ndarray,  # (n, d)
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (raw, probabilities, predictions) for one row block."""
    logits = block @ weights + intercepts
    if weights.shape[1] == 1:
        z = logits[:, 0]
        # Overflow-safe sigmoid: exp of a non-positive argument only.
        t = np.exp(-np.abs(z))
        p1 = np.where(z >= 0, 1.0 / (1.0 + t), t / (1.0 + t))
        probs = np.stack([1.0 - p1, p1], axis=1)
        raw = np.stack([-z, z], axis=1)
        pred = (p1 > threshold).astype(np.float64)
    else:
        m = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(m)
        probs = e / e.sum(axis=1, keepdims=True)
        raw = logits
        pred = np.argmax(logits, axis=1).astype(np.float64)
    return raw, probs, pred


def forest_forward(
    feature: np.ndarray,  # (T, N) int, -1 at leaves
    threshold: np.ndarray,  # (T, N)
    is_leaf: np.ndarray,  # (T, N) bool
    leaf_value: np.ndarray,  # (T, N, C) per-leaf class distribution
    max_depth: int,
    block: np.ndarray,  # (n, d)
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (raw vote mass, probabilities, predictions) for one block."""
    T = feature.shape[0]
    idx = forest_apply_leaves(feature, threshold, is_leaf, max_depth, block)
    n_classes = leaf_value.shape[2]
    probs = np.stack(
        [
            np.take_along_axis(leaf_value[:, :, c], idx, axis=1).mean(axis=0)
            for c in range(n_classes)
        ],
        axis=1,
    )
    raw = probs * T
    pred = np.argmax(probs, axis=1).astype(np.float64)
    return raw, probs, pred


def logistic_loss_grad(
    w: np.ndarray,  # (d, c) standardized-space weights
    b: np.ndarray,  # (c,)
    xs: np.ndarray,  # (rows, d) ALREADY standardized block
    y: np.ndarray,  # (rows,) integer labels
    binomial: bool,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Partition-local (Σ loss, Σ grad_w, Σ grad_b) for the logistic
    objective — the executor unit of work of the distributed fit (Spark's
    per-iteration treeAggregate); sums, not means, so partitions add.
    Mirrors ops/logistic.loss_fn exactly (softplus / log-softmax forms).
    """
    logits = xs @ w + b
    if binomial:
        z = logits[:, 0]
        yt = (y == 1).astype(np.float64)
        # softplus(z) - y z, stable
        loss = float(np.sum(np.logaddexp(0.0, z) - yt * z))
        t = np.exp(-np.abs(z))
        sig = np.where(z >= 0, 1.0 / (1.0 + t), t / (1.0 + t))
        r = (sig - yt)[:, None]  # (rows, 1)
    else:
        m = logits - logits.max(axis=1, keepdims=True)
        lse = m - np.log(np.exp(m).sum(axis=1, keepdims=True))
        rows = np.arange(xs.shape[0])
        loss = float(-np.sum(lse[rows, y.astype(np.int64)]))
        probs = np.exp(lse)
        probs[rows, y.astype(np.int64)] -= 1.0
        r = probs
    return loss, xs.T @ r, r.sum(axis=0)


def forest_apply_leaves(
    feature: np.ndarray,
    threshold: np.ndarray,
    is_leaf: np.ndarray,
    max_depth: int,
    block: np.ndarray,
) -> np.ndarray:
    """(T, n) leaf indices — the shared routing of the forest forwards."""
    T = feature.shape[0]
    n = block.shape[0]
    idx = np.zeros((T, n), dtype=np.int64)
    f_clip = np.maximum(feature, 0)
    for _ in range(max_depth):
        f = np.take_along_axis(f_clip, idx, axis=1)
        leaf = np.take_along_axis(is_leaf, idx, axis=1)
        thr = np.take_along_axis(threshold, idx, axis=1)
        xv = block[np.arange(n)[None, :], f]
        child = 2 * idx + 1 + (xv > thr)
        idx = np.where(leaf, idx, child)
    return idx


def forest_forward_reg(
    feature: np.ndarray,
    threshold: np.ndarray,
    is_leaf: np.ndarray,
    leaf_value: np.ndarray,  # (T, N, 1) per-leaf means
    max_depth: int,
    block: np.ndarray,
) -> np.ndarray:
    """(n,) regression predictions: mean of per-tree leaf means."""
    idx = forest_apply_leaves(feature, threshold, is_leaf, max_depth, block)
    return np.take_along_axis(leaf_value[:, :, 0], idx, axis=1).mean(axis=0)


# ----------------------------------------------------------------------
# Distributed random-forest fit: executor units of work (VERDICT r2 #3).
# Per level, each partition routes ITS rows through the broadcast partial
# forest and returns an additive histogram partial; treeReduce sums them
# and the driver decides splits with ops.trees.split_level — the same
# mapPartitions+treeAggregate structure as the covariance
# (RapidsRowMatrix.scala:170-233), applied per tree level.
# ----------------------------------------------------------------------


def bin_columns(x: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """(n, d) bin ids: bin = #{edges e : x > e} per feature — the numpy
    twin of ops/trees.bin_features (same convention, so raw thresholds
    are the winning bin's upper edge on both sides)."""
    out = np.empty(x.shape, dtype=np.int64)
    for f in range(x.shape[1]):
        out[:, f] = np.searchsorted(edges[f], x[:, f], side="left")
    return out


def forest_route(
    feature: np.ndarray,  # (T, N) int, -1 = no split
    threshold: np.ndarray,  # (T, N)
    x: np.ndarray,  # (n, d)
    level: int,
) -> np.ndarray:
    """(T, n) heap node ids of each row at ``level``; -1 = retired (the
    row's path hit a leaf above this level). Twins the routing step of
    ops/trees.grow_forest: descend LEFT on x[feature] <= threshold, which
    by the binning convention equals bin <= split bin."""
    T = feature.shape[0]
    n = x.shape[0]
    idx = np.zeros((T, n), dtype=np.int64)
    rows = np.arange(n)[None, :]
    for _ in range(level):
        active = idx >= 0
        safe = np.maximum(idx, 0)
        f = np.take_along_axis(feature, safe, axis=1)
        ok = f >= 0
        thr = np.take_along_axis(threshold, safe, axis=1)
        xv = x[rows, np.maximum(f, 0)]
        child = 2 * idx + 1 + (xv > thr)
        idx = np.where(active & ok, child, np.where(active, -1, idx))
    return idx


def level_histogram_partial(
    node_idx: np.ndarray,  # (T, n) from forest_route
    weights: np.ndarray,  # (T, n) per-tree sample weights
    x_binned: np.ndarray,  # (n, d)
    row_stats: np.ndarray,  # (n, S)
    offset: int,
    m_nodes: int,
    n_bins: int,
) -> np.ndarray:
    """(T, M, d, B, S) float64 histogram partial for one partition's rows
    — additive across partitions (the executor half of split_level)."""
    T, n = node_idx.shape
    d = x_binned.shape[1]
    S = row_stats.shape[1]
    hist = np.zeros((T, m_nodes * d * n_bins, S))
    feat_off = np.arange(d)[None, :] * n_bins
    for t in range(T):
        local = node_idx[t] - offset
        sel = (local >= 0) & (local < m_nodes) & (weights[t] > 0)
        if not np.any(sel):
            continue
        codes = (
            local[sel, None] * (d * n_bins) + feat_off + x_binned[sel]
        ).ravel()  # (n_sel * d,)
        for s in range(S):
            wts = np.repeat(weights[t, sel] * row_stats[sel, s], d)
            hist[t, :, s] += np.bincount(
                codes, weights=wts, minlength=m_nodes * d * n_bins
            )
    return hist.reshape(T, m_nodes, d, n_bins, S)


def node_totals_partial(
    node_idx: np.ndarray,
    weights: np.ndarray,
    row_stats: np.ndarray,
    offset: int,
    m_nodes: int,
) -> np.ndarray:
    """(T, M, S) per-node stat totals for one partition's rows (the
    bottom-level leaf statistics; additive across partitions)."""
    T = node_idx.shape[0]
    S = row_stats.shape[1]
    tot = np.zeros((T, m_nodes, S))
    for t in range(T):
        local = node_idx[t] - offset
        sel = (local >= 0) & (local < m_nodes) & (weights[t] > 0)
        if not np.any(sel):
            continue
        for s in range(S):
            tot[t, :, s] += np.bincount(
                local[sel], weights=weights[t, sel] * row_stats[sel, s],
                minlength=m_nodes,
            )
    return tot


def tree_weight_rng(seed: int, part_index: int):
    """Per-partition RNG for bootstrap weights, deterministic in
    (seed, partition index): every level's pass re-creates it and draws
    chunk by chunk in the same order, so executors re-derive identical
    weights without shipping state across Spark jobs."""
    return np.random.default_rng((int(seed) << 20) ^ (part_index + 1))


def draw_tree_weights(
    rng, n_trees: int, n_rows: int, rate: float, bootstrap: bool
) -> np.ndarray:
    """(T, n_rows) per-tree sample weights for one row chunk. Poisson(rate)
    with replacement / Bernoulli(rate) without — the scheme of
    ops/trees.sample_weights (the draw differs from the core's jax PRNG
    stream; both are valid bootstrap resamplings, and rate=1 without
    bootstrap is exactly all-ones on both sides)."""
    if not bootstrap and rate >= 1.0:
        return np.ones((n_trees, n_rows))
    if bootstrap:
        return rng.poisson(rate, (n_trees, n_rows)).astype(np.float64)
    return (rng.random((n_trees, n_rows)) < rate).astype(np.float64)


def soft_threshold(v: np.ndarray, t: float) -> np.ndarray:
    """Elementwise soft-threshold — the numpy twin of the L1 prox in
    ops/logistic.fit_logistic_elastic_net's FISTA step."""
    return np.sign(v) * np.maximum(np.abs(v) - t, 0.0)


def gram_matvec_partial(
    xs: np.ndarray, v: np.ndarray
) -> np.ndarray:
    """XsᵀXs·v partial for one standardized block — the executor unit of
    the distributed power iteration bounding the FISTA Lipschitz constant
    (the spectral-norm estimate of ops/logistic, one pass per step)."""
    return xs.T @ (xs @ v)


def knn_shard_topk(
    queries: np.ndarray,  # (nq, d) — broadcast to every shard
    items: np.ndarray,  # (m, d) — one executor's local index shard
    offset: int,  # global row index of items[0]
    k: int,
    metric: str = "euclidean",
) -> Tuple[np.ndarray, np.ndarray]:
    """Shard-local top-k — the executor unit of the SHARDED neighbor
    search (VERDICT r3 #5): each partition holds its rows as a local
    index, queries broadcast, and the per-shard (nq, k') candidates
    tree-merge with :func:`knn_merge_candidates`. The numpy twin of
    ops/knn.knn_sq_euclidean's block step (same expansion, same
    ascending-(distance, index) contract; indices are GLOBAL via
    ``offset``). k' = min(k, m) — a shard smaller than k contributes all
    its rows.
    """
    q = queries
    x = items
    if metric == "cosine":
        q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-30)
        x = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-30)
    d2 = (
        np.sum(q * q, axis=1)[:, None]
        - 2.0 * (q @ x.T)
        + np.sum(x * x, axis=1)[None, :]
    )
    np.maximum(d2, 0.0, out=d2)
    kk = min(k, x.shape[0])
    part = np.argpartition(d2, kk - 1, axis=1)[:, :kk]
    pd = np.take_along_axis(d2, part, axis=1)
    order = np.argsort(pd, axis=1, kind="stable")
    idx = np.take_along_axis(part, order, axis=1) + offset
    dist = np.take_along_axis(pd, order, axis=1)
    if metric == "euclidean":
        dist = np.sqrt(dist)
    elif metric == "cosine":
        dist = dist / 2.0
    return dist, idx.astype(np.int64)


def knn_merge_candidates(
    a: Tuple[np.ndarray, np.ndarray],
    b: Tuple[np.ndarray, np.ndarray],
    k: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge two per-shard candidate sets into the best k (the treeReduce
    combiner of the sharded search — same merge math as the device scan's
    candidate top-k)."""
    d = np.concatenate([a[0], b[0]], axis=1)
    i = np.concatenate([a[1], b[1]], axis=1)
    kk = min(k, d.shape[1])
    part = np.argpartition(d, kk - 1, axis=1)[:, :kk]
    pd = np.take_along_axis(d, part, axis=1)
    order = np.argsort(pd, axis=1, kind="stable")
    return (
        np.take_along_axis(pd, order, axis=1),
        np.take_along_axis(np.take_along_axis(i, part, axis=1), order, axis=1),
    )


__all__ = [
    "logistic_forward",
    "forest_forward",
    "forest_forward_reg",
    "forest_apply_leaves",
    "logistic_loss_grad",
    "bin_columns",
    "forest_route",
    "level_histogram_partial",
    "node_totals_partial",
    "tree_weight_rng",
    "draw_tree_weights",
    "soft_threshold",
    "gram_matvec_partial",
    "knn_shard_topk",
    "knn_merge_candidates",
]
