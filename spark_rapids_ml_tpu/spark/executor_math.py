"""Pure-numpy model forwards for Spark EXECUTOR processes.

The adapter's contract is that executors need numpy only — no JAX, no
chip (adapter.py module docstring; the reference's executors likewise run
JVM+CUDA-lib only, no Spark-side ML framework). Transform pandas_udfs
therefore close over plain numpy parameter arrays plus the functions in
THIS module (imported by reference on the executor, pulling in nothing but
numpy) — never over core model objects, whose modules import jax at the
top level.

The math mirrors the core kernels exactly: ``logistic_forward`` twins
ops/logistic.predict_logistic (raw = [-z, z] margins for binomial, logits
for multinomial); ``forest_forward`` twins ops/trees.forest_apply +
forest_predict_proba (heap-indexed routing, LEFT when x[feature] <=
threshold, probs = mean leaf distribution, raw = vote mass).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def logistic_forward(
    weights: np.ndarray,  # (d, 1) binomial or (d, C) multinomial
    intercepts: np.ndarray,  # (1,) or (C,)
    threshold: float,
    block: np.ndarray,  # (n, d)
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (raw, probabilities, predictions) for one row block."""
    logits = block @ weights + intercepts
    if weights.shape[1] == 1:
        z = logits[:, 0]
        # Overflow-safe sigmoid: exp of a non-positive argument only.
        t = np.exp(-np.abs(z))
        p1 = np.where(z >= 0, 1.0 / (1.0 + t), t / (1.0 + t))
        probs = np.stack([1.0 - p1, p1], axis=1)
        raw = np.stack([-z, z], axis=1)
        pred = (p1 > threshold).astype(np.float64)
    else:
        m = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(m)
        probs = e / e.sum(axis=1, keepdims=True)
        raw = logits
        pred = np.argmax(logits, axis=1).astype(np.float64)
    return raw, probs, pred


def forest_forward(
    feature: np.ndarray,  # (T, N) int, -1 at leaves
    threshold: np.ndarray,  # (T, N)
    is_leaf: np.ndarray,  # (T, N) bool
    leaf_value: np.ndarray,  # (T, N, C) per-leaf class distribution
    max_depth: int,
    block: np.ndarray,  # (n, d)
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (raw vote mass, probabilities, predictions) for one block."""
    T = feature.shape[0]
    idx = forest_apply_leaves(feature, threshold, is_leaf, max_depth, block)
    n_classes = leaf_value.shape[2]
    probs = np.stack(
        [
            np.take_along_axis(leaf_value[:, :, c], idx, axis=1).mean(axis=0)
            for c in range(n_classes)
        ],
        axis=1,
    )
    raw = probs * T
    pred = np.argmax(probs, axis=1).astype(np.float64)
    return raw, probs, pred


def logistic_loss_grad(
    w: np.ndarray,  # (d, c) standardized-space weights
    b: np.ndarray,  # (c,)
    xs: np.ndarray,  # (rows, d) ALREADY standardized block
    y: np.ndarray,  # (rows,) integer labels
    binomial: bool,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Partition-local (Σ loss, Σ grad_w, Σ grad_b) for the logistic
    objective — the executor unit of work of the distributed fit (Spark's
    per-iteration treeAggregate); sums, not means, so partitions add.
    Mirrors ops/logistic.loss_fn exactly (softplus / log-softmax forms).
    """
    logits = xs @ w + b
    if binomial:
        z = logits[:, 0]
        yt = (y == 1).astype(np.float64)
        # softplus(z) - y z, stable
        loss = float(np.sum(np.logaddexp(0.0, z) - yt * z))
        t = np.exp(-np.abs(z))
        sig = np.where(z >= 0, 1.0 / (1.0 + t), t / (1.0 + t))
        r = (sig - yt)[:, None]  # (rows, 1)
    else:
        m = logits - logits.max(axis=1, keepdims=True)
        lse = m - np.log(np.exp(m).sum(axis=1, keepdims=True))
        rows = np.arange(xs.shape[0])
        loss = float(-np.sum(lse[rows, y.astype(np.int64)]))
        probs = np.exp(lse)
        probs[rows, y.astype(np.int64)] -= 1.0
        r = probs
    return loss, xs.T @ r, r.sum(axis=0)


def forest_apply_leaves(
    feature: np.ndarray,
    threshold: np.ndarray,
    is_leaf: np.ndarray,
    max_depth: int,
    block: np.ndarray,
) -> np.ndarray:
    """(T, n) leaf indices — the shared routing of the forest forwards."""
    T = feature.shape[0]
    n = block.shape[0]
    idx = np.zeros((T, n), dtype=np.int64)
    f_clip = np.maximum(feature, 0)
    for _ in range(max_depth):
        f = np.take_along_axis(f_clip, idx, axis=1)
        leaf = np.take_along_axis(is_leaf, idx, axis=1)
        thr = np.take_along_axis(threshold, idx, axis=1)
        xv = block[np.arange(n)[None, :], f]
        child = 2 * idx + 1 + (xv > thr)
        idx = np.where(leaf, idx, child)
    return idx


def forest_forward_reg(
    feature: np.ndarray,
    threshold: np.ndarray,
    is_leaf: np.ndarray,
    leaf_value: np.ndarray,  # (T, N, 1) per-leaf means
    max_depth: int,
    block: np.ndarray,
) -> np.ndarray:
    """(n,) regression predictions: mean of per-tree leaf means."""
    idx = forest_apply_leaves(feature, threshold, is_leaf, max_depth, block)
    return np.take_along_axis(leaf_value[:, :, 0], idx, axis=1).mean(axis=0)


__all__ = [
    "logistic_forward",
    "forest_forward",
    "forest_forward_reg",
    "forest_apply_leaves",
]
