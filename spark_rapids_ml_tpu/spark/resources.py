"""Executor-side TPU resource binding.

The reference binds each Spark task to a GPU via
``TaskContext.get().resources()("gpu").addresses(0)``
(RapidsRowMatrix.scala:171-175), with a ``gpuId`` param override and the
driver hardcoding device 0 (:94-95). This module is the TPU equivalent:
resolve which chip THIS process should use, from (in priority order) an
explicit ordinal, the Spark task resource assignment, or default chip 0.

TPU specifics: a chip is single-tenant, so the discovery script +
``spark.task.resource.tpu.amount=1`` guarantee exactly one address per task;
the executor process must also pin JAX to that chip BEFORE backend init
(``TPU_VISIBLE_DEVICES``), since PJRT claims all local chips by default.
"""

from __future__ import annotations

import os
from typing import Optional


def task_tpu_address() -> Optional[str]:
    """Chip address assigned to the current Spark task, if running under
    pyspark with TPU task resources; None otherwise."""
    try:
        from pyspark import TaskContext  # type: ignore

        ctx = TaskContext.get()
        if ctx is None:
            return None
        resources = ctx.resources()
        if "tpu" not in resources:
            return None
        return resources["tpu"].addresses[0]
    except ImportError:
        return None


def resolve_device_ordinal(explicit: int = -1) -> int:
    """Resolve the chip ordinal for this process.

    Priority: explicit param (the reference's gpuId semantics) > Spark task
    resource > 0 (the reference's driver-side default, RapidsRowMatrix.scala:94).
    """
    if explicit >= 0:
        return explicit
    addr = task_tpu_address()
    if addr is not None:
        return int(addr)
    return 0


def pin_process_to_chip(ordinal: int) -> None:
    """Restrict this process's JAX/PJRT view to one chip.

    Must run before first JAX backend initialization — PJRT claims every
    local chip otherwise, breaking executor-per-chip deployments (the
    analogue of the reference's per-call ``cudaSetDevice``, which TPU
    runtimes do not offer post-init).
    """
    # Unconditional assignment: the platform often pre-exports
    # TPU_VISIBLE_DEVICES with ALL local chips (that very value is what the
    # discovery script enumerates) — setdefault would keep it and this
    # process would claim every single-tenant chip on the host.
    os.environ["TPU_VISIBLE_DEVICES"] = str(ordinal)
    os.environ["TPU_PROCESS_BOUNDS"] = "1,1,1"
    os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
