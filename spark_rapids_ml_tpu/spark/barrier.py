"""Barrier-stage gang deployment — the executable failure-recovery path.

The reference inherits its whole failure story from Spark: a CUDA error
throws through JNI (``rapidsml_jni.cu:101-153`` pattern), the task fails,
and Spark's scheduler retries it against the RDD lineage (SURVEY §5).
That per-task retry is WRONG for a multi-process jax.distributed fit: the
processes form a gang (one coordination service, collectives over every
member), so an individually retried task would rejoin a cohort whose
peers are dead or hung. The correct Spark deployment is a **barrier
stage** (``rdd.barrier().mapPartitions``): the scheduler launches all
tasks together and retries the WHOLE stage when any task fails — exactly
the relaunch-the-gang semantic the distributed fits need
(docs/PARITY.md "Failure detection / recovery"; previously prose-only,
VERDICT r4 #3).

This module is the small launcher that recipe describes:

  - :func:`barrier_gang_run` — run a per-partition task function as one
    barrier stage and collect its outputs; any task failure relaunches
    the gang (Spark's stage retry, up to spark.stage.maxConsecutiveAttempts).
  - :func:`gang_coordinates` — derive ``jax.distributed.initialize``
    arguments (coordinator address, process count/id) from the barrier
    task context, so each relaunched gang re-forms a FRESH cohort.

Works identically against genuine pyspark and the contract stub
(tests/pyspark_stub) — the shared suite exercises a mid-fit task kill
under both (tests/spark_contract_suite.py::TestBarrierGangRecovery).
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Iterator, Optional

from spark_rapids_ml_tpu.robustness.degrade import run_degradable
from spark_rapids_ml_tpu.robustness.retry import RetryPolicy
from spark_rapids_ml_tpu.utils.envknobs import env_int

DEFAULT_COORDINATOR_PORT = 8476  # jax.distributed's conventional port

# Driver-side STAGE resubmissions (whole-gang, on top of the scheduler's
# own spark.stage.maxConsecutiveAttempts budget). Default 1 = submit once
# and trust the scheduler, exactly the pre-policy behavior; raise it when
# the cluster's stage budget is too small for the failure domain.
BARRIER_RESUBMITS_ENV = "TPUML_BARRIER_RESUBMITS"


def barrier_gang_run(
    rdd,
    task_fn: Callable[[Optional[object], Iterator], Iterable],
    policy: Optional[RetryPolicy] = None,
    checkpoint_dir: Optional[str] = None,
) -> list:
    """Run ``task_fn(barrier_ctx, partition_iterator)`` over every
    partition as ONE barrier stage and return the collected outputs.

    ``barrier_ctx`` is the ``BarrierTaskContext`` (None only where a
    runtime lacks barrier support). The context's ``barrier()`` is called
    before ``task_fn`` so no member starts compute until the whole gang
    is scheduled — a member that fails at launch aborts the attempt
    before any collective can strand survivors. Any exception in any
    task relaunches ALL tasks (Spark barrier-stage retry); after the
    scheduler's stage-attempt limit the error reaches the driver, where
    the shared :class:`RetryPolicy` (robustness.retry) owns what happens
    next: classification (a ``ValueError`` from the task is a bug and
    re-raises untouched; a runtime failure is retryable), optional
    whole-stage resubmission (``TPUML_BARRIER_RESUBMITS``, default 1 =
    no resubmit), a profiler range per attempt, and one classified
    ``RetryExhaustedError`` when the budget is gone — never a hang.

    With ``TPUML_DEGRADE=cpu`` an exhausted budget degrades instead of
    raising: the partitions re-run on the driver as a plain (non-barrier,
    non-gang) stage with ``ctx=None`` — there is no cohort left to
    strand — under a structured :class:`DegradationWarning`.

    One-pass reductions simply refit from the same lineage on relaunch.
    ITERATIVE fits do better: pass ``checkpoint_dir`` (a path every
    executor can reach — the elastic-resume handoff) and each gang
    member exports it as ``TPUML_CHECKPOINT_DIR`` before running
    ``task_fn``, so a fit inside the task checkpoints its solver state
    (robustness/checkpoint.py) and a gang resubmitted after a dead
    worker — detected via the heartbeat timeout — resumes mid-solve
    from the last snapshot instead of iteration 0, resharding the
    restored state onto the fresh mesh
    (``parallel.distributed.replicate_state_onto_mesh``). Give the
    estimators STABLE uids: checkpoint identity is uid + param hash.
    Every driver-side resubmission bumps the ``gang.resubmit`` counter.

    Each gang member declares the ``barrier.attempt`` fault site
    (robustness.faults) right after the launch barrier, so chaos tests
    can kill attempt 0 and assert the relaunch refits bit-identically.

    The whole stage runs as ONE distributed trace: the driver opens (or
    joins) a trace under a ``barrier gang`` span, and a carrier dict —
    trace coordinates (``TPUML_TRACE_ID``/``TPUML_TRACE_PARENT``), the
    telemetry shard dir (``TPUML_TELEMETRY_DIR``) and the checkpoint dir
    — rides the task closure into every member, which exports it to its
    environment before compute. Each member's spans therefore carry the
    driver's trace id and parent to the driver's stage span, and each
    member process writes its own telemetry shard, so
    ``tools/tpuml_trace.py`` reassembles the gang fit as one tree.
    """
    from spark_rapids_ml_tpu.observability import events as _events
    from spark_rapids_ml_tpu.utils.tracing import (
        TraceColor,
        TraceRange,
        bump_counter,
    )

    with _events.run_scope("gang", "barrier_gang_run"), TraceRange(
        "barrier gang", TraceColor.CYAN
    ):
        carrier = _events.inject_env({})
        if checkpoint_dir is not None:
            from spark_rapids_ml_tpu.robustness.checkpoint import DIR_ENV

            carrier[DIR_ENV] = checkpoint_dir
        tdir = _events.telemetry_dir()
        if tdir is not None:
            carrier[_events.TELEMETRY_DIR_ENV] = tdir

        def wrapped(it):
            from pyspark import BarrierTaskContext

            from spark_rapids_ml_tpu.observability import events as _ev
            from spark_rapids_ml_tpu.observability.heartbeat import (
                heartbeat_scope,
            )
            from spark_rapids_ml_tpu.robustness.faults import fault_point

            # Export the carrier for the TASK'S lifetime only: executor
            # processes are reused across tasks (and under the stub the
            # "executor" IS the driver), so a permanent export would leak
            # this stage's trace into the next job's.
            saved = {k: os.environ.get(k) for k in carrier}
            os.environ.update(carrier)
            # A SIGTERM'd member (executor decommission, preemption)
            # flushes its shard + manifest from the handler — the
            # manifest-less-shard WARNING in the post-hoc merge is for
            # SIGKILL-class deaths only. On the driver-local stub this
            # is a no-op (not the main thread).
            undo_sigterm = _ev.install_sigterm_flush()
            try:
                if not _ev.enabled():
                    # A fresh executor process: wire its own telemetry
                    # shard (or event log) and pick up the driver's env
                    # trace. On the driver-local stub the sink is already
                    # live and the trace ambient — nothing to rewire.
                    _ev.configure()
                ctx = BarrierTaskContext.get()
                if ctx is not None:
                    ctx.barrier()
                fault_point("barrier.attempt")
                try:
                    member = int(ctx.partitionId()) if ctx is not None else 0
                except Exception:  # a stub context without partitionId
                    member = 0
                # Per-member heartbeat stream for the task's whole
                # lifetime (TPUML_GANG_HEARTBEAT_EVERY; observability/
                # heartbeat.py): a stuck member's heartbeat age grows
                # while its peers' stay near zero — visible BEFORE the
                # stage deadline fires.
                with _ev.trace_scope(
                    _ev.current_trace() or _ev.extract_env()
                ):
                    with heartbeat_scope(member, what="barrier"):
                        result = task_fn(ctx, it)
                        if hasattr(result, "__next__"):
                            # Drain generator tasks INSIDE the scopes: a
                            # lazily consumed body would otherwise run
                            # after the carrier is restored and the
                            # heartbeat stopped. Barrier tasks return
                            # per-member reductions, so materializing is
                            # cheap by construction.
                            result = list(result)
                        return result
            finally:
                undo_sigterm()
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        def fallback(it):
            # Degraded (driver-local) execution: no barrier, no gang,
            # ctx=None — and no barrier.attempt fault site, the gang is
            # what failed.
            return task_fn(None, it)

        if policy is None:
            # Deliberately NOT the generic TPUML_RETRY_MAX_ATTEMPTS knob:
            # the scheduler already retries the stage internally, so
            # driver-side resubmission has its own (default-off) budget.
            policy = RetryPolicy(
                max_attempts=env_int(BARRIER_RESUBMITS_ENV, 1, minimum=1)
            )

        def _on_resubmit(attempt, exc):
            bump_counter("gang.resubmit")
            _events.emit("barrier", action="resubmit", attempt=attempt,
                         error=type(exc).__name__)

        return run_degradable(
            lambda: policy.run(
                lambda: rdd.barrier().mapPartitions(wrapped).collect(),
                name="barrier.stage",
                on_retry=_on_resubmit,
            ),
            lambda: rdd.mapPartitions(fallback).collect(),
            what="barrier gang fit",
            site="barrier.attempt",
        )


def gang_coordinates(ctx, port: int = DEFAULT_COORDINATOR_PORT) -> dict:
    """``jax.distributed.initialize`` kwargs for one barrier gang member.

    The barrier task infos are the gang roster: task 0's host is the
    coordinator, the partition id is the process id. The task ATTEMPT
    number offsets the port: a failed attempt's coordinator process can
    outlive its task by up to the heartbeat timeout (default 100 s) while
    still bound to the port, so a relaunched gang reusing the same
    address would collide with — or worse, silently join — the dead
    cohort's coordination service. Each attempt binding a fresh port
    guarantees the relaunch forms a genuinely new service (the heartbeat
    fail-fast in parallel/distributed.py detects the death; this
    launcher provides the rebirth).
    """
    infos = ctx.getTaskInfos()
    host = infos[0].address.split(":")[0]
    attempt = int(getattr(ctx, "attemptNumber", lambda: 0)())
    return {
        "coordinator_address": f"{host}:{port + attempt}",
        "num_processes": len(infos),
        "process_id": int(ctx.partitionId()),
    }


def _as_feature_row(value):
    """One partition element as a dense numpy feature row (pyspark Vectors
    expose ``toArray``; anything else must already be array-like)."""
    import numpy as np

    return np.asarray(
        value.toArray() if hasattr(value, "toArray") else value,
        dtype=np.float64,
    )


def _gang_extract(it, labeled: bool):
    """Materialize one member's partition as its LOCAL fit dataset:
    a (rows, d) matrix, or an ``(x, y)`` pair when ``labeled`` (elements
    are (features, label) sequences — the ``select(features, label).rdd``
    row shape)."""
    import numpy as np

    xs, ys = [], []
    for r in it:
        if labeled:
            xs.append(_as_feature_row(r[0]))
            ys.append(float(r[1]))
        else:
            xs.append(_as_feature_row(r[0] if isinstance(r, (tuple, list)) else r))
    x = np.stack(xs) if xs else np.zeros((0, 0))
    return (x, np.asarray(ys)) if labeled else x


def gang_fit(
    estimator,
    rdd,
    labeled: bool = False,
    extract: Optional[Callable[[Iterator], object]] = None,
    port: Optional[int] = None,
    policy: Optional[RetryPolicy] = None,
    checkpoint_dir: Optional[str] = None,
) -> list:
    """Fit ``estimator`` gang-parallel: one barrier stage, one gang member
    per partition, each calling the PUBLIC ``fit()`` on its local rows.

    This is the chip-per-executor deployment of the core estimators
    (ROADMAP item 4) as a driver-side one-liner::

        models = gang_fit(PCA().setK(2), df.rdd.map(lambda r: r[0]))

    Per member: the partition materializes as that member's LOCAL dataset
    (``labeled`` switches to (x, y) extraction; ``extract`` overrides the
    whole mapping), :func:`gang_coordinates` derives the member's
    jax.distributed coordinates from the barrier roster, and — for gangs
    of more than one member — they export as the ``TPUML_COORDINATOR`` /
    ``TPUML_NUM_PROCESSES`` / ``TPUML_PROCESS_ID`` knobs for the fit's
    lifetime. The member then copies the estimator, sets
    ``deployMode='gang'``, and calls ``fit`` — ``Estimator._join_gang``
    brings up the cohort, the ingest funnel assembles the globally
    sharded array, and the solver's reductions psum across members, so
    every member returns the identical whole-dataset model (the driver
    conventionally keeps ``models[0]``).

    All of :func:`barrier_gang_run`'s machinery rides along unchanged:
    whole-stage relaunch with fresh coordinator ports per attempt, the
    trace/telemetry carrier (each member writes its own shard; the merged
    trace shows one gang fit), per-member heartbeats, and the
    ``checkpoint_dir`` elastic-resume handoff. ``port`` defaults to the
    ``TPUML_GANG_PORT`` knob. NOTE: the contract stub runs barrier tasks
    sequentially on the driver, so only single-member gangs (one
    partition) are testable under the stub — a multi-member stub gang
    would deadlock in the bring-up; real clusters schedule members
    concurrently (tests/multiproc_gang_fit_worker.py is the real
    2-process proof).
    """
    if port is None:
        port = env_int("TPUML_GANG_PORT", DEFAULT_COORDINATOR_PORT, minimum=1)
    do_extract = extract if extract is not None else (
        lambda it: _gang_extract(it, labeled)
    )

    def task(ctx, it):
        local = do_extract(it)
        gang_env = {}
        if ctx is not None and hasattr(ctx, "getTaskInfos"):
            coords = gang_coordinates(ctx, port)
            if int(coords["num_processes"]) > 1:
                gang_env = {
                    "TPUML_COORDINATOR": coords["coordinator_address"],
                    "TPUML_NUM_PROCESSES": str(coords["num_processes"]),
                    "TPUML_PROCESS_ID": str(coords["process_id"]),
                }
        saved = {k: os.environ.get(k) for k in gang_env}
        os.environ.update(gang_env)
        try:
            member = estimator.copy().setDeployMode("gang")
            return [member.fit(local)]
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    return barrier_gang_run(
        rdd, task, policy=policy, checkpoint_dir=checkpoint_dir
    )


def serving_gang_run(
    rdd,
    rendezvous: str,
    policy: Optional[RetryPolicy] = None,
) -> list:
    """Run serving-tier members as ONE barrier stage: each partition's
    task body is :func:`serving.worker.serve_member` — publish a contact
    card into ``rendezvous``, accept the router connection, serve until
    shutdown. Partition elements are member ids (ints); an empty
    partition falls back to its partition id, so the common
    ``parallelize(range(n), n)`` roster works with either convention.

    Blocks until the whole gang drains (the router's ``close``), so the
    router runs it on a background thread. All of
    :func:`barrier_gang_run`'s machinery — launch barrier, whole-stage
    relaunch, per-member heartbeats, the trace/telemetry carrier —
    applies unchanged; the PR 7 carrier is what merges every member's
    serving events into the router's trace. NOTE: the contract stub runs
    barrier tasks sequentially on the driver, so only a single-member
    gang is testable under the stub — a real cluster schedules members
    concurrently.
    """
    from spark_rapids_ml_tpu.serving.worker import serve_member

    def task(ctx, it):
        members = sorted(int(i) for i in it)
        if not members:
            try:
                members = [int(ctx.partitionId())] if ctx is not None else [0]
            except Exception:
                members = [0]
        return [serve_member(m, rendezvous) for m in members]

    return barrier_gang_run(rdd, task, policy=policy)
