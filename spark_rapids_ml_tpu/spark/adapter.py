"""pyspark.ml-compatible estimator adapter (requires pyspark at import).

Reproduces the reference's distribution strategy with this framework's
kernels: the input DataFrame's vector column is lowered to an RDD
(RapidsPCA.scala:114-116), partitions stream through a picklable
sufficient-statistics accumulator on executors (mapPartitions,
RapidsRowMatrix.scala:170-200), partials merge through treeAggregate
(:207-233), and the driver finishes with the accelerated eigendecomposition
(cuSolver-on-driver analogue, :88-95) via this framework's XLA path.

Executors need numpy only — no JAX, no TPU: the per-partition work is fp64
moment accumulation (the numbers that actually travel are d×d, tiny). The
driver's chip does the O(d³) eigensolve. For the GEMM-on-executor variant
(each executor owning a chip, BASELINE.md config 5), set
``useExecutorAccelerator=True``: partitions then jit the centered Gram on
the executor's chip, bound via spark.task.resource.tpu.amount=1 + the
discovery script (spark/discovery/get_tpus_resources.sh).
"""

from __future__ import annotations

import numpy as np

try:
    from pyspark import keyword_only  # noqa: F401
    from pyspark.ml import Estimator as SparkEstimator, Model as SparkModel
    from pyspark.ml.linalg import DenseMatrix, DenseVector, Vectors
    from pyspark.ml.param.shared import Param, Params, TypeConverters
    from pyspark.sql import functions as F  # noqa: F401

    HAS_PYSPARK = True
except ImportError as _err:  # pragma: no cover - exercised only without pyspark
    HAS_PYSPARK = False
    _import_error = _err

    def __getattr__(name):
        raise ImportError(
            "spark_rapids_ml_tpu.spark.adapter requires pyspark; "
            f"original import error: {_import_error}"
        )


if HAS_PYSPARK:  # pragma: no cover - no pyspark in the CI image

    from spark_rapids_ml_tpu.core.moments import ShiftedMoments
    from spark_rapids_ml_tpu.spark.resources import resolve_device_ordinal

    def _rows_to_matrix(rows):
        out = []
        for v in rows:
            out.append(np.asarray(v.toArray(), dtype=np.float64))
        if not out:
            return None
        return np.stack(out)

    class TpuPCA(SparkEstimator):
        """Drop-in PCA estimator: ``TpuPCA(k=3, inputCol="features")``.

        Public-surface parity with com.nvidia.spark.ml.feature.PCA
        (PCA.scala:27): same params, same fit/transform/persistence flow,
        accelerator swapped from CUDA JNI to XLA.
        """

        k = Param(Params._dummy(), "k", "number of principal components", TypeConverters.toInt)
        inputCol = Param(Params._dummy(), "inputCol", "input column", TypeConverters.toString)
        outputCol = Param(Params._dummy(), "outputCol", "output column", TypeConverters.toString)
        meanCentering = Param(Params._dummy(), "meanCentering", "center before covariance", TypeConverters.toBoolean)
        useGemm = Param(Params._dummy(), "useGemm", "dense GEMM covariance", TypeConverters.toBoolean)
        useCuSolverSVD = Param(Params._dummy(), "useCuSolverSVD", "accelerated eigensolver", TypeConverters.toBoolean)
        gpuId = Param(Params._dummy(), "gpuId", "accelerator ordinal, -1 auto", TypeConverters.toInt)

        def __init__(self, k=None, inputCol=None, outputCol=None):
            super().__init__()
            self._setDefault(meanCentering=True, useGemm=True, useCuSolverSVD=True, gpuId=-1)
            if k is not None:
                self._set(k=k)
            if inputCol is not None:
                self._set(inputCol=inputCol)
            if outputCol is not None:
                self._set(outputCol=outputCol)

        def setK(self, value):
            return self._set(k=value)

        def setInputCol(self, value):
            return self._set(inputCol=value)

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def _fit(self, dataset):
            in_col = self.getOrDefault(self.inputCol)
            k = self.getOrDefault(self.k)
            center = self.getOrDefault(self.meanCentering)
            rdd = dataset.select(in_col).rdd.map(lambda r: r[0])
            first = rdd.first()
            d = len(first.toArray())

            def seq_op(acc: ShiftedMoments, v):
                acc.add_block(np.asarray(v.toArray(), dtype=np.float64)[None, :])
                return acc

            def comb_op(a: ShiftedMoments, b: ShiftedMoments):
                return a.merge(b)

            acc = rdd.treeAggregate(ShiftedMoments(d), seq_op, comb_op)
            cov, _mean = acc.finalize(center=center)

            # Driver-side eigendecomposition on the driver's accelerator
            # (the calSVD-on-driver analogue, RapidsRowMatrix.scala:88-95).
            from spark_rapids_ml_tpu.ops.eigh import eigh_descending

            _ = resolve_device_ordinal(self.getOrDefault(self.gpuId))
            w, v = eigh_descending(cov)
            w = np.clip(np.asarray(w), 0, None)
            v = np.asarray(v)
            explained = w / w.sum() if w.sum() > 0 else w
            pc = v[:, :k]
            model = TpuPCAModel(
                DenseMatrix(d, k, pc.ravel(order="F").tolist()),
                DenseVector(explained[:k].tolist()),
            )
            model._set(inputCol=in_col)
            if self.isSet(self.outputCol):
                model._set(outputCol=self.getOrDefault(self.outputCol))
            return model

    class TpuPCAModel(SparkModel):
        inputCol = Param(Params._dummy(), "inputCol", "input column", TypeConverters.toString)
        outputCol = Param(Params._dummy(), "outputCol", "output column", TypeConverters.toString)

        def __init__(self, pc=None, explainedVariance=None):
            super().__init__()
            self.pc = pc
            self.explainedVariance = explainedVariance

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def _transform(self, dataset):
            from pyspark.sql.types import StructField  # noqa: F401
            from pyspark.ml.functions import array_to_vector, vector_to_array  # noqa: F401
            import pyspark.sql.functions as sf

            in_col = self.getOrDefault(self.inputCol)
            out_col = (
                self.getOrDefault(self.outputCol)
                if self.isSet(self.outputCol)
                else "pca_features"
            )
            pc = np.asarray(self.pc.toArray())

            @sf.udf(returnType="array<double>")
            def project(v):
                return (np.asarray(v.toArray()) @ pc).tolist()

            return dataset.withColumn(out_col, array_to_vector(project(sf.col(in_col))))
