"""pyspark.ml-compatible estimator adapter (requires pyspark at import).

Reproduces the reference's distribution strategy with this framework's
kernels: the input DataFrame's vector column is lowered to an RDD
(RapidsPCA.scala:114-116), partitions stream through a picklable
sufficient-statistics accumulator on executors (mapPartitions,
RapidsRowMatrix.scala:170-200), partials merge through treeAggregate
(:207-233), and the driver finishes with the accelerated eigendecomposition
(cuSolver-on-driver analogue, :88-95) via this framework's XLA path.

Executors need numpy only — no JAX, no TPU: the per-partition work is fp64
moment accumulation in row batches (the numbers that actually travel are
d×d, tiny). The driver finishes with the eigendecomposition: on the chip
resolved from ``gpuId``/task resources when ``useCuSolverSVD=True`` (the
calSVD-on-driver analogue), or NumPy on the driver CPU when False (the
reference's breeze-SVD fallback, RapidsRowMatrix.scala:110-123).
``useGemm`` is accepted for parity and recorded in params; both covariance
routes share the one streaming accumulator here (the reference's spr/gemm
split reflected a cuBLAS API choice with no TPU analogue — both its paths
produce the same covariance, RapidsRowMatrix.scala:149-257).
"""

from __future__ import annotations

import numpy as np

try:
    from pyspark import keyword_only  # noqa: F401
    from pyspark.ml import Estimator as SparkEstimator, Model as SparkModel
    from pyspark.ml.linalg import DenseMatrix, DenseVector, Vectors
    from pyspark.ml.param.shared import Param, Params, TypeConverters
    from pyspark.sql import functions as F  # noqa: F401

    HAS_PYSPARK = True
except ImportError as _err:  # pragma: no cover - exercised only without pyspark
    HAS_PYSPARK = False
    _import_error = _err

    def __getattr__(name):
        raise ImportError(
            "spark_rapids_ml_tpu.spark.adapter requires pyspark; "
            f"original import error: {_import_error}"
        )


if HAS_PYSPARK:  # pragma: no cover - no pyspark in the CI image

    from spark_rapids_ml_tpu.core.moments import ShiftedMoments
    from spark_rapids_ml_tpu.core.persistence import MLReadable
    from spark_rapids_ml_tpu.spark.resources import resolve_device_ordinal

    class TpuPCA(SparkEstimator, MLReadable):
        """Drop-in PCA estimator: ``TpuPCA(k=3, inputCol="features")``.

        Public-surface parity with com.nvidia.spark.ml.feature.PCA
        (PCA.scala:27): same params, same fit/transform/persistence flow,
        accelerator swapped from CUDA JNI to XLA.
        """

        k = Param(Params._dummy(), "k", "number of principal components", TypeConverters.toInt)
        inputCol = Param(Params._dummy(), "inputCol", "input column", TypeConverters.toString)
        outputCol = Param(Params._dummy(), "outputCol", "output column", TypeConverters.toString)
        meanCentering = Param(Params._dummy(), "meanCentering", "center before covariance", TypeConverters.toBoolean)
        useGemm = Param(Params._dummy(), "useGemm", "dense GEMM covariance", TypeConverters.toBoolean)
        useCuSolverSVD = Param(Params._dummy(), "useCuSolverSVD", "accelerated eigensolver", TypeConverters.toBoolean)
        gpuId = Param(Params._dummy(), "gpuId", "accelerator ordinal, -1 auto", TypeConverters.toInt)

        def __init__(self, k=None, inputCol=None, outputCol=None):
            super().__init__()
            self._setDefault(meanCentering=True, useGemm=True, useCuSolverSVD=True, gpuId=-1)
            if k is not None:
                self._set(k=k)
            if inputCol is not None:
                self._set(inputCol=inputCol)
            if outputCol is not None:
                self._set(outputCol=outputCol)

        def setK(self, value):
            return self._set(k=value)

        def setInputCol(self, value):
            return self._set(inputCol=value)

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def setMeanCentering(self, value):
            return self._set(meanCentering=value)

        def setUseGemm(self, value):
            return self._set(useGemm=value)

        def setUseCuSolverSVD(self, value):
            return self._set(useCuSolverSVD=value)

        def setGpuId(self, value):
            return self._set(gpuId=value)

        @classmethod
        def load(cls, path):
            # Overrides MLReadable.load: pyspark's Param typeConverter API
            # differs from the core Params', so values are set by name.
            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class="TpuPCA")
            est = cls()
            for source in (metadata.get("defaultParamMap", {}), metadata.get("paramMap", {})):
                for name, value in source.items():
                    if est.hasParam(name):
                        est._set(**{name: value})
            return est

        def _fit(self, dataset):
            in_col = self.getOrDefault(self.inputCol)
            k = self.getOrDefault(self.k)
            center = self.getOrDefault(self.meanCentering)
            rdd = dataset.select(in_col).rdd.map(lambda r: r[0])
            first = rdd.first()
            d = len(first.toArray())

            def part_op(rows):
                # Batch rows before the rank-b update: one numpy GEMM per
                # batch instead of a Python call + (1,d) outer product per
                # row (the mapPartitions block streaming of
                # RapidsRowMatrix.scala:170-200).
                acc = ShiftedMoments(d)
                batch = []
                for v in rows:
                    batch.append(np.asarray(v.toArray(), dtype=np.float64))
                    if len(batch) >= 4096:
                        acc.add_block(np.stack(batch))
                        batch = []
                if batch:
                    acc.add_block(np.stack(batch))
                return [acc]

            acc = rdd.mapPartitions(part_op).treeReduce(lambda a, b: a.merge(b))
            cov, _mean = acc.finalize(center=center)

            # Driver-side eigendecomposition (the calSVD-on-driver analogue,
            # RapidsRowMatrix.scala:88-95) on the chip gpuId/task resources
            # resolve to, or the NumPy fallback path when useCuSolverSVD is
            # off (the breeze-SVD branch, RapidsRowMatrix.scala:110-123).
            # Without x64, jit would silently truncate the carefully
            # accumulated fp64 covariance to f32 — use the host path then.
            import jax

            if self.getOrDefault(self.useCuSolverSVD) and jax.config.jax_enable_x64:
                from spark_rapids_ml_tpu.ops.eigh import eigh_descending

                ordinal = resolve_device_ordinal(self.getOrDefault(self.gpuId))
                devices = jax.devices()
                if ordinal >= len(devices):
                    raise ValueError(
                        f"gpuId/task resource resolved to chip {ordinal}, but only "
                        f"{len(devices)} device(s) are visible"
                    )
                with jax.default_device(devices[ordinal]):
                    w, v = eigh_descending(cov)
            else:
                from spark_rapids_ml_tpu.ops.eigh import eigh_descending_host

                w, v = eigh_descending_host(cov)
            w = np.clip(np.asarray(w), 0, None)
            v = np.asarray(v)
            explained = w / w.sum() if w.sum() > 0 else w
            pc = v[:, :k]
            model = TpuPCAModel(
                DenseMatrix(d, k, pc.ravel(order="F").tolist()),
                DenseVector(explained[:k].tolist()),
            )
            model._set(inputCol=in_col)
            if self.isSet(self.outputCol):
                model._set(outputCol=self.getOrDefault(self.outputCol))
            return model

    class TpuPCAModel(SparkModel, MLReadable):
        inputCol = Param(Params._dummy(), "inputCol", "input column", TypeConverters.toString)
        outputCol = Param(Params._dummy(), "outputCol", "output column", TypeConverters.toString)

        def __init__(self, pc=None, explainedVariance=None):
            super().__init__()
            self.pc = pc
            self.explainedVariance = explainedVariance

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def _transform(self, dataset):
            from pyspark.ml.functions import array_to_vector, vector_to_array
            from pyspark.sql.functions import col, pandas_udf

            in_col = self.getOrDefault(self.inputCol)
            out_col = (
                self.getOrDefault(self.outputCol)
                if self.isSet(self.outputCol)
                else "pca_features"
            )
            pc = np.asarray(self.pc.toArray())

            # Vectorized batch projection (one NumPy GEMM per Arrow batch) —
            # the working version of the reference's disabled GPU batch
            # transform (RapidsPCA.scala:172-185); a per-row scalar UDF would
            # pay a pickle round-trip + Python call per row.
            @pandas_udf("array<double>")
            def project(series):
                import pandas as pd

                block = np.stack([np.asarray(v, dtype=np.float64) for v in series])
                return pd.Series(list(block @ pc))

            return dataset.withColumn(
                out_col, array_to_vector(project(vector_to_array(col(in_col))))
            )

        def _save_impl(self, path):
            # Reference on-disk layout (RapidsPCA.scala:207-255): params JSON
            # under metadata/, single-row parquet of (pc, explainedVariance)
            # under data/ — via the same writers the core models use.
            from spark_rapids_ml_tpu.core import persistence as P

            P.save_metadata(self, path, class_name="TpuPCAModel")
            P.save_data(
                path,
                {
                    "pc": ("matrix", np.asarray(self.pc.toArray())),
                    "explainedVariance": (
                        "vector",
                        np.asarray(self.explainedVariance.toArray()),
                    ),
                },
            )

        @classmethod
        def load(cls, path):
            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class="TpuPCAModel")
            data = P.load_data(path)
            pc = np.asarray(data["pc"])
            ev = np.asarray(data["explainedVariance"])
            model = cls(
                DenseMatrix(pc.shape[0], pc.shape[1], pc.ravel(order="F").tolist()),
                DenseVector(ev.tolist()),
            )
            # pyspark Param values set by name (pyspark's typeConverter API
            # differs from the core Params', so core get_and_set_params does
            # not apply here).
            for source in (metadata.get("defaultParamMap", {}), metadata.get("paramMap", {})):
                for name, value in source.items():
                    if model.hasParam(name):
                        model._set(**{name: value})
            return model
