"""pyspark.ml-compatible estimator adapter (requires pyspark at import).

Reproduces the reference's distribution strategy with this framework's
kernels: the input DataFrame's vector column is lowered to an RDD
(RapidsPCA.scala:114-116), partitions stream through a picklable
sufficient-statistics accumulator on executors (mapPartitions,
RapidsRowMatrix.scala:170-200), partials merge through treeAggregate
(:207-233), and the driver finishes with the accelerated eigendecomposition
(cuSolver-on-driver analogue, :88-95) via this framework's XLA path.

For the training families (PCA, KMeans, LinearRegression,
LogisticRegression in every regularization mode, and both RandomForest
families) the fit is DISTRIBUTED and executors need numpy only — no JAX,
no TPU: the per-partition work is moment / gradient / histogram
accumulation in row batches (the numbers that travel are d×d covariances,
(d, c) gradients, or per-level split histograms — never rows), and
transform UDFs close over plain numpy parameters +
``spark/executor_math.py``. The driver finishes each iteration: the
eigendecomposition/solve on the chip resolved from ``gpuId``/task
resources when ``useCuSolverSVD=True`` (the calSVD-on-driver analogue) or
NumPy when False (the breeze-SVD fallback, RapidsRowMatrix.scala:110-123);
L-BFGS-B / FISTA steps for the GLMs; split selection for the forests
(ops.trees.split_level — the same math as the core solver). The NEIGHBOR
families (kNN/ANN/DBSCAN/UMAP) instead collect the item set to the
driver-attached chip, as the modern cuML spark deployment does for the
same families; their kneighbors UDFs ship the accelerated index to
executors.
``useGemm`` is accepted for parity and recorded in params; both covariance
routes share the one streaming accumulator here (the reference's spr/gemm
split reflected a cuBLAS API choice with no TPU analogue — both its paths
produce the same covariance, RapidsRowMatrix.scala:149-257).
"""

from __future__ import annotations

import numpy as np

try:
    from pyspark import keyword_only  # noqa: F401
    from pyspark.ml import Estimator as SparkEstimator, Model as SparkModel
    from pyspark.ml.linalg import DenseMatrix, DenseVector
    from pyspark.ml.param.shared import Param, Params, TypeConverters
    from pyspark.sql import functions as F  # noqa: F401

    HAS_PYSPARK = True
except ImportError as _err:  # pragma: no cover - exercised only without pyspark
    HAS_PYSPARK = False
    _import_error = _err

    def __getattr__(name):
        raise ImportError(
            "spark_rapids_ml_tpu.spark.adapter requires pyspark; "
            f"original import error: {_import_error}"
        )


if HAS_PYSPARK:  # pragma: no cover - no pyspark in the CI image

    from spark_rapids_ml_tpu.core.moments import ShiftedMoments
    from spark_rapids_ml_tpu.core.persistence import MLReadable
    from spark_rapids_ml_tpu.spark.resources import resolve_device_ordinal

    class _TpuEstimatorPersistence(MLReadable):
        """Estimator save/load (DefaultParamsWritable parity): metadata
        JSON holds the params; load restores them by name onto a fresh
        instance of the concrete class."""

        def _save_impl(self, path):
            from spark_rapids_ml_tpu.core import persistence as P

            P.save_metadata(self, path, class_name=type(self).__name__)

        @classmethod
        def load(cls, path):
            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class=cls.__name__)
            est = _set_params_from_metadata(cls(), metadata)
            # DefaultParamsReader restores the uid via _resetUid, which
            # also re-parents the instance params and rebuilds the maps —
            # a bare `.uid = ...` would orphan every param (pyspark
            # Params._shouldOwn rejects them afterwards).
            est._resetUid(metadata["uid"])
            return est

    class _TpuCoreModelPersistence(MLReadable):
        """Model save/load for adapters that WRAP a core model: metadata
        at the root, the core model under <path>/core. Subclasses set
        ``_core_class`` to a zero-arg callable returning the core model
        class (lazy import keeps executors jax-free)."""

        _core_class = None

        def _save_impl(self, path):
            import os as _os

            from spark_rapids_ml_tpu.core import persistence as P

            P.save_metadata(self, path, class_name=type(self).__name__)
            self._core.save(_os.path.join(path, "core"))

        @classmethod
        def load(cls, path):
            import os as _os

            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class=cls.__name__)
            core = cls._core_class().load(_os.path.join(path, "core"))
            model = _set_params_from_metadata(cls(core), metadata)
            model._resetUid(metadata["uid"])  # see _TpuEstimatorPersistence.load
            return model

    def _set_params_from_metadata(obj, metadata):
        """Restore pyspark Param values by name from core metadata JSON —
        defaults go back into the DEFAULT map (DefaultParamsReader
        semantics: a load-save round trip must not migrate defaults into
        paramMap or flip isSet())."""
        for name, value in metadata.get("defaultParamMap", {}).items():
            if obj.hasParam(name):
                param = obj.getParam(name)
                obj._defaultParamMap[param] = param.typeConverter(value)
        for name, value in metadata.get("paramMap", {}).items():
            if obj.hasParam(name):
                obj._set(**{name: value})
        return obj


    class TpuPCA(SparkEstimator, _TpuEstimatorPersistence):
        """Drop-in PCA estimator: ``TpuPCA(k=3, inputCol="features")``.

        Public-surface parity with com.nvidia.spark.ml.feature.PCA
        (PCA.scala:27): same params, same fit/transform/persistence flow,
        accelerator swapped from CUDA JNI to XLA.
        """

        k = Param(Params._dummy(), "k", "number of principal components", TypeConverters.toInt)
        inputCol = Param(Params._dummy(), "inputCol", "input column", TypeConverters.toString)
        outputCol = Param(Params._dummy(), "outputCol", "output column", TypeConverters.toString)
        meanCentering = Param(Params._dummy(), "meanCentering", "center before covariance", TypeConverters.toBoolean)
        useGemm = Param(Params._dummy(), "useGemm", "dense GEMM covariance", TypeConverters.toBoolean)
        useCuSolverSVD = Param(Params._dummy(), "useCuSolverSVD", "accelerated eigensolver", TypeConverters.toBoolean)
        gpuId = Param(Params._dummy(), "gpuId", "accelerator ordinal, -1 auto", TypeConverters.toInt)

        def __init__(self, k=None, inputCol=None, outputCol=None):
            super().__init__()
            self._setDefault(meanCentering=True, useGemm=True, useCuSolverSVD=True, gpuId=-1)
            if k is not None:
                self._set(k=k)
            if inputCol is not None:
                self._set(inputCol=inputCol)
            if outputCol is not None:
                self._set(outputCol=outputCol)

        def setK(self, value):
            return self._set(k=value)

        def setInputCol(self, value):
            return self._set(inputCol=value)

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def setMeanCentering(self, value):
            return self._set(meanCentering=value)

        def setUseGemm(self, value):
            return self._set(useGemm=value)

        def setUseCuSolverSVD(self, value):
            return self._set(useCuSolverSVD=value)

        def setGpuId(self, value):
            return self._set(gpuId=value)

        def _fit(self, dataset):
            in_col = self.getOrDefault(self.inputCol)
            k = self.getOrDefault(self.k)
            center = self.getOrDefault(self.meanCentering)
            rdd = dataset.select(in_col).rdd.map(lambda r: r[0])
            first = rdd.first()
            d = len(first.toArray())

            def part_op(rows):
                # Batch rows before the rank-b update: one numpy GEMM per
                # batch instead of a Python call + (1,d) outer product per
                # row (the mapPartitions block streaming of
                # RapidsRowMatrix.scala:170-200).
                acc = ShiftedMoments(d)
                for chunk in _row_batches(rows):
                    acc.add_block(_dense_chunk(chunk, col=None))
                return [acc]

            acc = rdd.mapPartitions(part_op).treeReduce(lambda a, b: a.merge(b))
            cov, _mean = acc.finalize(center=center)

            # Driver-side eigendecomposition (the calSVD-on-driver analogue,
            # RapidsRowMatrix.scala:88-95) on the chip gpuId/task resources
            # resolve to, or the NumPy fallback path when useCuSolverSVD is
            # off (the breeze-SVD branch, RapidsRowMatrix.scala:110-123).
            # Without x64, jit would silently truncate the carefully
            # accumulated fp64 covariance to f32 — use the host path then.
            import jax

            if self.getOrDefault(self.useCuSolverSVD) and jax.config.jax_enable_x64:
                from spark_rapids_ml_tpu.ops.eigh import eigh_descending

                ordinal = resolve_device_ordinal(self.getOrDefault(self.gpuId))
                devices = jax.local_devices()
                if ordinal >= len(devices):
                    raise ValueError(
                        f"gpuId/task resource resolved to chip {ordinal}, but only "
                        f"{len(devices)} device(s) are visible"
                    )
                with jax.default_device(devices[ordinal]):
                    w, v = eigh_descending(cov)
            else:
                from spark_rapids_ml_tpu.ops.eigh import eigh_descending_host

                w, v = eigh_descending_host(cov)
            w = np.clip(np.asarray(w), 0, None)
            v = np.asarray(v)
            explained = w / w.sum() if w.sum() > 0 else w
            pc = v[:, :k]
            model = TpuPCAModel(
                DenseMatrix(d, k, pc.ravel(order="F").tolist()),
                DenseVector(explained[:k].tolist()),
            )
            model._set(inputCol=in_col)
            if self.isSet(self.outputCol):
                model._set(outputCol=self.getOrDefault(self.outputCol))
            return model

    class TpuPCAModel(SparkModel, MLReadable):
        inputCol = Param(Params._dummy(), "inputCol", "input column", TypeConverters.toString)
        outputCol = Param(Params._dummy(), "outputCol", "output column", TypeConverters.toString)

        def __init__(self, pc=None, explainedVariance=None):
            super().__init__()
            self.pc = pc
            self.explainedVariance = explainedVariance

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def _transform(self, dataset):
            from pyspark.ml.functions import array_to_vector, vector_to_array
            from pyspark.sql.functions import col, pandas_udf

            in_col = self.getOrDefault(self.inputCol)
            out_col = (
                self.getOrDefault(self.outputCol)
                if self.isSet(self.outputCol)
                else "pca_features"
            )
            pc = np.asarray(self.pc.toArray())

            # Vectorized batch projection (one NumPy GEMM per Arrow batch) —
            # the working version of the reference's disabled GPU batch
            # transform (RapidsPCA.scala:172-185); a per-row scalar UDF would
            # pay a pickle round-trip + Python call per row.
            @pandas_udf("array<double>")
            def project(series):
                import pandas as pd

                block = np.stack([np.asarray(v, dtype=np.float64) for v in series])
                return pd.Series(list(block @ pc))

            return dataset.withColumn(
                out_col, array_to_vector(project(vector_to_array(col(in_col))))
            )

        def _save_impl(self, path):
            # Reference on-disk layout (RapidsPCA.scala:207-255): params JSON
            # under metadata/, single-row parquet of (pc, explainedVariance)
            # under data/ — via the same writers the core models use.
            from spark_rapids_ml_tpu.core import persistence as P

            P.save_metadata(self, path, class_name="TpuPCAModel")
            P.save_data(
                path,
                {
                    "pc": ("matrix", np.asarray(self.pc.toArray())),
                    "explainedVariance": (
                        "vector",
                        np.asarray(self.explainedVariance.toArray()),
                    ),
                },
            )

        @classmethod
        def load(cls, path):
            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class="TpuPCAModel")
            data = P.load_data(path)
            pc = np.asarray(data["pc"])
            ev = np.asarray(data["explainedVariance"])
            model = cls(
                DenseMatrix(pc.shape[0], pc.shape[1], pc.ravel(order="F").tolist()),
                DenseVector(ev.tolist()),
            )
            # pyspark Param values set by name (pyspark's typeConverter API
            # differs from the core Params', so core get_and_set_params does
            # not apply here).
            return _set_params_from_metadata(model, metadata)

    # ------------------------------------------------------------------
    # Shared adapter plumbing for the non-PCA families
    # ------------------------------------------------------------------

    def _collect_features(dataset, features_col):
        """Materialize the feature vectors on the driver (partition-
        streamed fetch) — the fit-side collect of the driver-chip
        families."""
        xs = [
            np.asarray(row[0].toArray(), dtype=np.float64)
            for row in dataset.select(features_col).rdd.toLocalIterator()
        ]
        if not xs:
            raise ValueError("empty dataset")
        return np.stack(xs)

    def _prediction_udf(fn, returns="double"):
        """Vectorized Arrow-batch prediction column (one numpy/JAX batch op
        per Arrow batch — the working version of the reference's disabled
        batched transform, RapidsPCA.scala:172-185). ``returns="integer"``
        emits an int column (Spark's KMeansModel prediction schema)."""
        from pyspark.sql.functions import pandas_udf

        out_np = np.int32 if returns == "integer" else np.float64

        @pandas_udf(returns)
        def predict(series):
            import pandas as pd

            if len(series) == 0:  # empty partition: nothing to score
                return pd.Series([], dtype=out_np)
            block = np.stack([np.asarray(v, dtype=np.float64) for v in series])
            return pd.Series(np.asarray(fn(block), dtype=out_np))

        return predict

    def _row_batches(rows, size=4096):
        """Yield lists of up to ``size`` rows from a partition iterator —
        THE executor batching convention (one numpy op per batch instead
        of per-row Python work); shared by every mapPartitions op here."""
        batch = []
        for r in rows:
            batch.append(r)
            if len(batch) >= size:
                yield batch
                batch = []
        if batch:
            yield batch

    def _dense_chunk(chunk, col=0):
        """One (rows, d) float64 block from a chunk of Rows (or Vectors when
        ``col is None``) — the densify half of the batching convention."""
        if col is None:
            return np.stack([np.asarray(v.toArray(), dtype=np.float64) for v in chunk])
        return np.stack(
            [np.asarray(r[col].toArray(), dtype=np.float64) for r in chunk]
        )

    class _BroadcastCall:
        """Executor-side shim: tasks ship only the Broadcast HANDLE; the
        heavyweight callable (training matrix + fitted values) serializes
        ONCE at broadcast() time — the reference's broadcast of the
        column means (RapidsRowMatrix.scala:162-166), applied to the
        transform closures (VERDICT r3 #7)."""

        def __init__(self, bc):
            self.bc = bc

        def __call__(self, block):
            return self.bc.value(block)

    class _FittedOrTransform:
        """Callable mapping EXACT training rows to their fitted outputs
        (labels / coordinates) and everything else through the core
        model's transform. Hashing happens at the TRAIN dtype on both
        sides — core models may store f32 (no-x64 platforms), and hashing
        the incoming f64 rows directly would never match. Duplicate
        training rows resolve to the first occurrence. A plain class (not
        a closure) so models stay picklable after caching one."""

        def __init__(self, train, fitted_values, transform_fn):
            # +0.0 collapses -0.0 to +0.0 before byte-hashing: equal rows
            # with representation-distinct zeros must hit the same bucket
            # on both the train and query side.
            self.train = np.ascontiguousarray(train) + 0.0
            self.fitted = np.asarray(fitted_values, dtype=np.float64)
            self.transform_fn = transform_fn
            self.lookup = {}
            for i in range(self.train.shape[0]):
                self.lookup.setdefault(self.train[i].tobytes(), i)

        def __call__(self, block):
            block = np.asarray(block, dtype=np.float64)
            q = np.ascontiguousarray(block.astype(self.train.dtype, copy=False)) + 0.0
            hits = np.asarray([self.lookup.get(row.tobytes(), -1) for row in q])
            shape = (
                (block.shape[0],)
                if self.fitted.ndim == 1
                else (block.shape[0], self.fitted.shape[1])
            )
            out = np.empty(shape)
            if np.any(hits >= 0):
                out[hits >= 0] = self.fitted[hits[hits >= 0]]
            new = hits < 0
            if np.any(new):
                out[new] = np.asarray(
                    self.transform_fn(block[new]), dtype=np.float64
                )
            return out

    def _sq_dists(x: np.ndarray, centers: np.ndarray) -> np.ndarray:
        """(n, k) squared distances via ||x||^2 - 2 x c^T + ||c||^2: one
        (n, d) x (d, k) matmul, no (n, k, d) intermediate (the memory
        discipline of ops/kmeans.py, numpy edition for executors)."""
        d2 = (
            (x * x).sum(axis=1)[:, None]
            - 2.0 * (x @ centers.T)
            + (centers * centers).sum(axis=1)[None, :]
        )
        return np.maximum(d2, 0.0)

    class _TpuPredictorParams(Params):
        featuresCol = Param(Params._dummy(), "featuresCol", "features column", TypeConverters.toString)
        labelCol = Param(Params._dummy(), "labelCol", "label column", TypeConverters.toString)
        predictionCol = Param(Params._dummy(), "predictionCol", "prediction column", TypeConverters.toString)

        def setFeaturesCol(self, value):
            return self._set(featuresCol=value)

        def setLabelCol(self, value):
            return self._set(labelCol=value)

        def setPredictionCol(self, value):
            return self._set(predictionCol=value)

    # ------------------------------------------------------------------
    # KMeans — genuinely distributed Lloyd iterations over the RDD
    # ------------------------------------------------------------------

    class TpuKMeans(SparkEstimator, _TpuPredictorParams, _TpuEstimatorPersistence):
        """Distributed k-means: per-iteration partition-local assignment
        stats (numpy on executors) merged via treeReduce, centers updated
        on the driver — the mllib KMeans aggregation structure with this
        framework's driver-side finishing."""

        k = Param(Params._dummy(), "k", "number of clusters", TypeConverters.toInt)
        maxIter = Param(Params._dummy(), "maxIter", "max iterations", TypeConverters.toInt)
        tol = Param(Params._dummy(), "tol", "convergence tolerance", TypeConverters.toFloat)
        seed = Param(Params._dummy(), "seed", "random seed", TypeConverters.toInt)

        def __init__(self, k=2, featuresCol="features", predictionCol="prediction"):
            super().__init__()
            self._setDefault(
                k=2, maxIter=20, tol=1e-4, seed=0,
                featuresCol="features", predictionCol="prediction",
            )
            self._set(k=k, featuresCol=featuresCol, predictionCol=predictionCol)

        def setK(self, value):
            return self._set(k=value)

        def setMaxIter(self, value):
            return self._set(maxIter=value)

        def setTol(self, value):
            return self._set(tol=value)

        def setSeed(self, value):
            return self._set(seed=value)

        def _fit(self, dataset):
            k = self.getOrDefault(self.k)
            rdd = dataset.select(self.getOrDefault(self.featuresCol)).rdd.map(
                lambda r: r[0]
            )
            # Lloyd re-reads the data every iteration: persist once instead
            # of recomputing the select+deserialize lineage maxIter times
            # (Spark's own KMeans caches the normalized data the same way).
            rdd.persist()
            try:
                # takeSample, not take: take() reads the FIRST partitions,
                # and row order often correlates with structure (sorted
                # labels, time order) — seeding from one partition
                # collapses clusters.
                seed_rows = rdd.takeSample(
                    False, max(10 * k, k), self.getOrDefault(self.seed)
                )
                if not seed_rows:
                    raise ValueError("empty dataset")
                sample = np.stack(
                    [np.asarray(v.toArray(), dtype=np.float64) for v in seed_rows]
                )
                if sample.shape[0] < k:
                    raise ValueError(
                        f"k={k} exceeds the number of rows {sample.shape[0]}"
                    )
                d = sample.shape[1]
                # k-means++ seeding on the driver sample (numpy,
                # deterministic); distances via the Gram expansion
                # ||x||^2 - 2 x c^T + ||c||^2 — never a (n, k, d) tensor
                # (the ops/kmeans.py memory discipline).
                rng = np.random.default_rng(self.getOrDefault(self.seed))
                centers = sample[rng.integers(sample.shape[0])][None, :]
                while centers.shape[0] < k:
                    d2 = np.min(_sq_dists(sample, centers), axis=1)
                    probs = d2 / d2.sum() if d2.sum() > 0 else None
                    centers = np.concatenate(
                        [centers, sample[rng.choice(sample.shape[0], p=probs)][None]]
                    )

                for _ in range(self.getOrDefault(self.maxIter)):
                    c = centers  # closure-captured broadcast analogue

                    def part_op(rows, c=c, k=k, d=d):
                        sums = np.zeros((k, d))
                        counts = np.zeros(k)
                        sse = 0.0
                        for chunk in _row_batches(rows):
                            x = _dense_chunk(chunk, col=None)
                            d2 = _sq_dists(x, c)
                            a = np.argmin(d2, axis=1)
                            np.add.at(sums, a, x)
                            np.add.at(counts, a, 1.0)
                            sse += float(d2[np.arange(len(a)), a].sum())
                        return [(sums, counts, sse)]

                    sums, counts, _sse = rdd.mapPartitions(part_op).treeReduce(
                        lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2])
                    )
                    new_centers = np.where(
                        counts[:, None] > 0,
                        sums / np.maximum(counts, 1.0)[:, None],
                        centers,
                    )
                    shift = float(
                        np.max(np.linalg.norm(new_centers - centers, axis=1))
                    )
                    centers = new_centers
                    if shift < self.getOrDefault(self.tol):
                        break
            finally:
                rdd.unpersist()

            model = TpuKMeansModel(centers)
            model._set(
                featuresCol=self.getOrDefault(self.featuresCol),
                predictionCol=self.getOrDefault(self.predictionCol),
            )
            return model

    class TpuKMeansModel(SparkModel, _TpuPredictorParams, MLReadable):
        def __init__(self, centers=None):
            super().__init__()
            self._setDefault(featuresCol="features", predictionCol="prediction")
            self._centers = None if centers is None else np.asarray(centers, dtype=np.float64)

        def clusterCenters(self):
            return [c for c in self._centers]

        def _transform(self, dataset):
            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col

            centers = self._centers

            def assign(block):
                return np.argmin(_sq_dists(block, centers), axis=1)

            # Integer prediction column — Spark's KMeansModel emits
            # IntegerType, and drop-in pipelines match on column type.
            return dataset.withColumn(
                self.getOrDefault(self.predictionCol),
                _prediction_udf(assign, returns="integer")(
                    vector_to_array(col(self.getOrDefault(self.featuresCol)))
                ),
            )

        def _save_impl(self, path):
            from spark_rapids_ml_tpu.core import persistence as P

            P.save_metadata(self, path, class_name="TpuKMeansModel")
            P.save_data(path, {"clusterCenters": ("matrix", self._centers)})

        @classmethod
        def load(cls, path):
            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class="TpuKMeansModel")
            data = P.load_data(path)
            model = cls(np.asarray(data["clusterCenters"]))
            return _set_params_from_metadata(model, metadata)

    # ------------------------------------------------------------------
    # LinearRegression — distributed normal-equation moments + fp64 solve
    # ------------------------------------------------------------------

    class TpuLinearRegression(SparkEstimator, _TpuPredictorParams, _TpuEstimatorPersistence):
        """Distributed least squares: executors accumulate the [X|y]
        shifted second moments (numpy, picklable), treeReduce merges, the
        driver solves the normal equations in fp64
        (ops.linear.solve_normal_host) — one data pass, d x d on the wire."""

        regParam = Param(Params._dummy(), "regParam", "L2 regularization", TypeConverters.toFloat)
        elasticNetParam = Param(Params._dummy(), "elasticNetParam", "L1 mixing (must be 0)", TypeConverters.toFloat)
        fitIntercept = Param(Params._dummy(), "fitIntercept", "fit intercept", TypeConverters.toBoolean)
        standardization = Param(Params._dummy(), "standardization", "standardize penalty", TypeConverters.toBoolean)

        def __init__(self, featuresCol="features", labelCol="label", predictionCol="prediction"):
            super().__init__()
            self._setDefault(
                regParam=0.0, elasticNetParam=0.0, fitIntercept=True,
                standardization=True, featuresCol="features", labelCol="label",
                predictionCol="prediction",
            )
            self._set(
                featuresCol=featuresCol, labelCol=labelCol, predictionCol=predictionCol
            )

        def setRegParam(self, value):
            return self._set(regParam=value)

        def setElasticNetParam(self, value):
            return self._set(elasticNetParam=value)

        def setFitIntercept(self, value):
            return self._set(fitIntercept=value)

        def setStandardization(self, value):
            return self._set(standardization=value)

        def _fit(self, dataset):
            if self.getOrDefault(self.elasticNetParam) != 0.0:
                raise ValueError(
                    "TpuLinearRegression's distributed normal-equation path "
                    "supports only L2 (elasticNetParam must be 0)"
                )
            f_col = self.getOrDefault(self.featuresCol)
            l_col = self.getOrDefault(self.labelCol)
            rdd = dataset.select(f_col, l_col).rdd
            first = rdd.first()
            d = len(first[0].toArray())

            def part_op(rows, d=d):
                acc = ShiftedMoments(d + 1)
                for chunk in _row_batches(rows):
                    acc.add_block(
                        np.stack(
                            [
                                np.concatenate(
                                    [
                                        np.asarray(row[0].toArray(), dtype=np.float64),
                                        [float(row[1])],
                                    ]
                                )
                                for row in chunk
                            ]
                        )
                    )
                return [acc]

            acc = rdd.mapPartitions(part_op).treeReduce(lambda a, b: a.merge(b))
            raw, mean = acc.finalize(center=False)  # raw 2nd moment / (n-1)
            n = float(acc.n_rows)
            raw = raw * (n - 1.0)
            from spark_rapids_ml_tpu.ops.linear import solve_normal_host

            coef, intercept = solve_normal_host(
                raw[:d, :d],
                raw[:d, d],
                mean[:d] * n,
                mean[d] * n,
                n,
                reg_param=self.getOrDefault(self.regParam),
                fit_intercept=self.getOrDefault(self.fitIntercept),
                standardization=self.getOrDefault(self.standardization),
            )
            model = TpuLinearRegressionModel(
                DenseVector(np.asarray(coef).tolist()), float(intercept)
            )
            model._set(
                featuresCol=f_col,
                labelCol=l_col,
                predictionCol=self.getOrDefault(self.predictionCol),
            )
            return model

    class TpuLinearRegressionModel(SparkModel, _TpuPredictorParams, MLReadable):
        def __init__(self, coefficients=None, intercept=0.0):
            super().__init__()
            self._setDefault(
                featuresCol="features", labelCol="label", predictionCol="prediction"
            )
            self.coefficients = coefficients
            self.intercept = float(intercept)

        def _transform(self, dataset):
            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col

            coef = np.asarray(self.coefficients.toArray())
            b = self.intercept
            return dataset.withColumn(
                self.getOrDefault(self.predictionCol),
                _prediction_udf(lambda block: block @ coef + b)(
                    vector_to_array(col(self.getOrDefault(self.featuresCol)))
                ),
            )

        def _save_impl(self, path):
            from spark_rapids_ml_tpu.core import persistence as P

            P.save_metadata(self, path, class_name="TpuLinearRegressionModel")
            P.save_data(
                path,
                {
                    "coefficients": ("vector", np.asarray(self.coefficients.toArray())),
                    "intercept": ("scalar", self.intercept),
                },
            )

        @classmethod
        def load(cls, path):
            from spark_rapids_ml_tpu.core import persistence as P

            metadata = P.load_metadata(path, expected_class="TpuLinearRegressionModel")
            data = P.load_data(path)
            model = cls(
                DenseVector(np.asarray(data["coefficients"]).tolist()),
                float(data["intercept"]),
            )
            return _set_params_from_metadata(model, metadata)

    # ------------------------------------------------------------------
    # LogisticRegression / RandomForest — distributed fits: executors
    # accumulate gradient/histogram partials, the driver runs the
    # optimizer / split-selection step each iteration
    # ------------------------------------------------------------------

    class _TpuProbabilisticParams(_TpuPredictorParams):
        probabilityCol = Param(Params._dummy(), "probabilityCol", "probability column", TypeConverters.toString)
        rawPredictionCol = Param(Params._dummy(), "rawPredictionCol", "raw prediction column", TypeConverters.toString)

        def setProbabilityCol(self, value):
            return self._set(probabilityCol=value)

        def setRawPredictionCol(self, value):
            return self._set(rawPredictionCol=value)

    def _classifier_transform(forward, n_classes, adapter):
        """Append rawPrediction / probability / prediction columns from a
        numpy-only ``forward(block) -> (raw, probs, pred)`` callable.

        ONE forward pass per Arrow batch: the combined [raw | probs | pred]
        scores land in a temporary array column, and the three public
        columns are cheap slices of it. ``forward`` must close over plain
        numpy arrays + spark.executor_math functions only — executors have
        numpy, not JAX (module docstring contract).
        """

        def _apply(dataset):
            from pyspark.ml.functions import array_to_vector, vector_to_array
            from pyspark.sql.functions import col, pandas_udf

            feats = vector_to_array(
                col(adapter.getOrDefault(adapter.featuresCol))
            )

            @pandas_udf("array<double>")
            def scores(series):
                import pandas as pd

                if len(series) == 0:
                    return pd.Series([], dtype=object)
                block = np.stack(
                    [np.asarray(v, dtype=np.float64) for v in series]
                )
                raw, probs, pred = forward(block)
                return pd.Series(
                    list(np.concatenate([raw, probs, pred[:, None]], axis=1))
                )

            def slice_vec(lo, hi):
                @pandas_udf("array<double>")
                def s(series):
                    import pandas as pd

                    return pd.Series([np.asarray(v)[lo:hi] for v in series])

                return s

            @pandas_udf("double")
            def last(series):
                import pandas as pd

                return pd.Series([float(np.asarray(v)[-1]) for v in series])

            tmp = "_tpu_scores"
            out = dataset.withColumn(tmp, scores(feats))
            c = n_classes
            out = out.withColumn(
                adapter.getOrDefault(adapter.rawPredictionCol),
                array_to_vector(slice_vec(0, c)(col(tmp))),
            )
            out = out.withColumn(
                adapter.getOrDefault(adapter.probabilityCol),
                array_to_vector(slice_vec(c, 2 * c)(col(tmp))),
            )
            out = out.withColumn(
                adapter.getOrDefault(adapter.predictionCol), last(col(tmp))
            )
            return out.drop(tmp)

        return _apply

    class TpuLogisticRegression(SparkEstimator, _TpuProbabilisticParams, _TpuEstimatorPersistence):
        maxIter = Param(Params._dummy(), "maxIter", "max iterations", TypeConverters.toInt)
        regParam = Param(Params._dummy(), "regParam", "regularization", TypeConverters.toFloat)
        elasticNetParam = Param(Params._dummy(), "elasticNetParam", "L1/L2 mixing", TypeConverters.toFloat)

        def __init__(self, featuresCol="features", labelCol="label"):
            super().__init__()
            self._setDefault(
                maxIter=100, regParam=0.0, elasticNetParam=0.0,
                featuresCol="features", labelCol="label",
                predictionCol="prediction", probabilityCol="probability",
                rawPredictionCol="rawPrediction",
            )
            self._set(featuresCol=featuresCol, labelCol=labelCol)

        def setMaxIter(self, value):
            return self._set(maxIter=value)

        def setRegParam(self, value):
            return self._set(regParam=value)

        def setElasticNetParam(self, value):
            return self._set(elasticNetParam=value)

        def _fit(self, dataset):
            # ONE distributed path (VERDICT r2 #3 — no full-dataset
            # collect): the gang deploy switch. Partitions coalesce onto
            # the gang roster (TPUML_GANG_FIT_MEMBERS), each barrier
            # member materializes only ITS rows and calls the public
            # core fit with deployMode='gang' — the solver's psum'd
            # reductions produce the identical whole-dataset model on
            # every member, for L2, elastic-net, and multinomial alike.
            # This replaces the driver-orchestrated L-BFGS/FISTA twins
            # that duplicated the core solvers in executor numpy.
            from spark_rapids_ml_tpu.classification import (
                LogisticRegression as CoreLogisticRegression,
            )
            from spark_rapids_ml_tpu.spark.barrier import (
                _gang_extract,
                gang_fit,
            )
            from spark_rapids_ml_tpu.utils.envknobs import env_int

            f_col = self.getOrDefault(self.featuresCol)
            l_col = self.getOrDefault(self.labelCol)
            rdd = dataset.select(f_col, l_col).rdd
            members = env_int("TPUML_GANG_FIT_MEMBERS", 1, minimum=1)
            if rdd.getNumPartitions() != members:
                rdd = rdd.coalesce(members)

            def extract(it):
                # Executor-side label validation (Spark rejects
                # non-integer labels; silent truncation would fold 1.5
                # into class 1) — the partition never leaves the member.
                x, y = _gang_extract(it, labeled=True)
                bad = (y != np.rint(y)) | (y < 0)
                if np.any(bad):
                    raise ValueError(
                        "labels must be non-negative integers, got "
                        f"{y[bad][0]!r}"
                    )
                return x, y

            core = (
                CoreLogisticRegression()
                .setMaxIter(self.getOrDefault(self.maxIter))
                .setRegParam(self.getOrDefault(self.regParam))
                .setElasticNetParam(self.getOrDefault(self.elasticNetParam))
            )
            models = gang_fit(core, rdd, extract=extract)
            return self._wrap(models[0])

        def _wrap(self, core):
            model = TpuLogisticRegressionModel(core)
            for p in ("featuresCol", "labelCol", "predictionCol", "probabilityCol", "rawPredictionCol"):
                model._set(**{p: self.getOrDefault(getattr(self, p))})
            return model

    class TpuLogisticRegressionModel(SparkModel, _TpuProbabilisticParams, _TpuCoreModelPersistence):
        def __init__(self, core_model=None):
            super().__init__()
            self._setDefault(
                featuresCol="features", labelCol="label",
                predictionCol="prediction", probabilityCol="probability",
                rawPredictionCol="rawPrediction",
            )
            self._core = core_model

        @property
        def coefficients(self):
            return DenseVector(self._core.coefficients.tolist())

        @property
        def intercept(self):
            return float(self._core.intercept)

        def _transform(self, dataset):
            import functools

            from spark_rapids_ml_tpu.spark import executor_math

            # Extract plain numpy params on the driver; the closure ships
            # arrays + a numpy-only module function to executors (no JAX).
            forward = functools.partial(
                executor_math.logistic_forward,
                np.asarray(self._core.weights, dtype=np.float64),
                np.asarray(self._core.intercepts, dtype=np.float64),
                float(self._core.getThreshold()),
            )
            return _classifier_transform(forward, self._core.numClasses, self)(dataset)

        @staticmethod
        def _core_class():
            from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegressionModel

            return LogisticRegressionModel

    # ------------------------------------------------------------------
    # Distributed random-forest fit (VERDICT r2 #3): per-level executor
    # histogram partials merged by treeReduce, split decisions on the
    # driver with the SAME math the core solver uses
    # (ops.trees.split_level) — the mapPartitions+treeAggregate structure
    # of the covariance (RapidsRowMatrix.scala:170-233) applied per tree
    # level. No row ever travels to the driver except a bounded quantile
    # sample (the split-finding sample, as in Spark MLlib's findSplits).
    # ------------------------------------------------------------------

    # Rows the driver may fetch for quantile split finding; tests shrink
    # it to prove the no-full-collect property at small n.
    _QUANTILE_SAMPLE_CAP = 65536

    def _fit_forest_rdd(
        rdd, *, n_trees, max_depth, max_bins, seed, impurity, classification,
        subsampling_rate, bootstrap, feature_subset,
    ):
        """Grow a Forest over an RDD of (features, label) rows without
        collecting the dataset: ``max_depth + 2`` passes total (label
        stats, one histogram pass per level, bottom-level totals), each a
        mapPartitionsWithIndex + treeReduce of additive numpy partials.
        Executors re-derive bootstrap weights per level from
        (seed, partition index, in-partition position) instead of
        shipping state — the same deterministic per-partition-seeded
        scheme as Spark MLlib's BaggedPoint (XORShiftRandom(seed +
        partitionIndex)), with the same contract: the input lineage must
        place rows deterministically across recomputes (true for
        deterministic sources; a round-robin ``repartition`` upstream
        voids it there exactly as it does for MLlib's forests)."""
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.random_forest import (
            resolve_feature_subset,
        )
        from spark_rapids_ml_tpu.ops.trees import (
            Forest,
            _impurity,
            _leaf_prediction,
            split_level,
        )
        from spark_rapids_ml_tpu.spark import executor_math as EM

        rdd.persist()
        try:
            d = len(rdd.first()[0].toArray())

            def label_op(rows):
                n_loc, s, y_max, bad = 0, 0.0, 0.0, False
                for chunk in _row_batches(rows):
                    ys = np.asarray([float(r[1]) for r in chunk])
                    n_loc += ys.size
                    s += float(ys.sum())
                    y_max = max(y_max, float(ys.max()))
                    bad = bad or bool(
                        np.any(ys != np.rint(ys)) or np.any(ys < 0)
                    )
                return [(n_loc, s, y_max, bad)] if n_loc else []

            n, y_sum, y_max, y_bad = rdd.mapPartitions(label_op).treeReduce(
                lambda a, b: (
                    a[0] + b[0], a[1] + b[1], max(a[2], b[2]), a[3] or b[3]
                )
            )
            if classification:
                if y_bad:
                    raise ValueError("labels must be non-negative integers")
                n_classes = max(int(y_max) + 1, 2)
                y_mean = 0.0
                s_dim = n_classes
            else:
                n_classes = 0
                y_mean = y_sum / n
                s_dim = 3

            # Quantile edges from a BOUNDED row sample (Spark MLlib's
            # findSplits samples the same way); same quantile definition
            # as ops.trees.quantize_features, at the core's f32.
            n_bins = min(max_bins, max(2, n))
            # One-pass uniform bounded draw. Plain sample().collect() only
            # bounds the driver fetch in EXPECTATION; truncating the
            # overdraw with take() would drop rows from the trailing
            # partitions (a systematic bias on range-partitioned data);
            # takeSample would fix both but runs its own count() job over
            # the full dataset even though the treeReduce above already
            # produced n. So: Bernoulli-draw at a modestly inflated
            # fraction (one pass, rows cross the wire ~1.2×cap), then
            # subsample UNIFORMLY to the cap driver-side — the retained
            # sample is strictly bounded and unbiased.
            if n <= _QUANTILE_SAMPLE_CAP:
                sample_rows = rdd.collect()
            else:
                fraction = min(1.0, 1.2 * _QUANTILE_SAMPLE_CAP / n)
                drawn = rdd.sample(False, fraction, seed).collect()
                if len(drawn) > _QUANTILE_SAMPLE_CAP:
                    pick = np.random.default_rng(seed).choice(
                        len(drawn), size=_QUANTILE_SAMPLE_CAP, replace=False
                    )
                    drawn = [drawn[i] for i in pick]
                sample_rows = drawn
            if not sample_rows:  # pathological draw: fall back
                sample_rows = rdd.take(min(n, _QUANTILE_SAMPLE_CAP))
            sx = np.stack(
                [np.asarray(r[0].toArray(), dtype=np.float64) for r in sample_rows]
            ).astype(np.float32)
            qs = np.arange(1, n_bins, dtype=np.float64) / n_bins
            edges = np.quantile(sx, qs, axis=0).T.astype(np.float32)  # (d, B-1)
            edges64 = edges.astype(np.float64)

            m_sub = resolve_feature_subset(
                feature_subset, d, n_trees, classification
            )
            # Same key derivation as models.random_forest._fit_forest, so
            # the per-level feature-subset draws match the core's.
            _, k_feat = jax.random.split(jax.random.key(seed))

            if classification:
                def stats_of(y):
                    rs = np.zeros((y.size, n_classes))
                    rs[np.arange(y.size), y.astype(np.int64)] = 1.0
                    return rs
            else:
                def stats_of(y, mu=y_mean):
                    yc = y - mu
                    return np.stack([np.ones_like(yc), yc, yc * yc], axis=1)

            n_total = 2 ** (max_depth + 1) - 1
            T = n_trees
            feature = np.full((T, n_total), -1, dtype=np.int32)
            threshold = np.zeros((T, n_total), dtype=np.float32)
            is_leaf = np.zeros((T, n_total), dtype=bool)
            s_out = s_dim if classification else 1
            leaf_value = np.zeros((T, n_total, s_out), dtype=np.float32)
            node_weight = np.zeros((T, n_total), dtype=np.float32)
            node_gain = np.zeros((T, n_total), dtype=np.float32)
            node_imp = np.zeros((T, n_total), dtype=np.float32)

            def partials_op(level, offset, m_nodes, want_hist,
                            feat_b, thr_b):
                """Executor op: route rows through the broadcast partial
                forest, return ONE additive partial (histogram or node
                totals) for this partition."""

                def op(pi, rows):
                    rng = EM.tree_weight_rng(seed, pi)
                    acc = None
                    for chunk in _row_batches(rows):
                        x = _dense_chunk(chunk)
                        y = np.asarray([float(r[1]) for r in chunk])
                        w = EM.draw_tree_weights(
                            rng, T, x.shape[0], subsampling_rate, bootstrap
                        )
                        rs = stats_of(y)
                        idx = EM.forest_route(feat_b, thr_b, x, level)
                        if want_hist:
                            part = EM.level_histogram_partial(
                                idx, w, EM.bin_columns(x, edges64), rs,
                                offset, m_nodes, n_bins,
                            )
                        else:
                            part = EM.node_totals_partial(
                                idx, w, rs, offset, m_nodes
                            )
                        acc = part if acc is None else acc + part
                    return [] if acc is None else [acc]

                return op

            for level in range(max_depth):
                offset = 2**level - 1
                m_nodes = 2**level
                hist = rdd.mapPartitionsWithIndex(
                    partials_op(level, offset, m_nodes, True,
                                feature.copy(), threshold.copy())
                ).treeReduce(lambda a, b: a + b)
                f_b, b_b, g_b, ok, total, w_par = split_level(
                    jnp.asarray(hist, dtype=jnp.float32), k_feat, level,
                    impurity=impurity, feat_subset=m_sub,
                )
                f_b, b_b, g_b = np.asarray(f_b), np.asarray(b_b), np.asarray(g_b)
                ok = np.asarray(ok)
                sl = slice(offset, offset + m_nodes)
                feature[:, sl] = np.where(ok, f_b, -1)
                threshold[:, sl] = np.where(ok, edges[f_b, b_b], 0.0)
                is_leaf[:, sl] = ~ok
                leaf_value[:, sl, :] = np.asarray(
                    _leaf_prediction(total, impurity)
                )
                node_weight[:, sl] = np.asarray(w_par)
                node_gain[:, sl] = np.where(ok, g_b, 0.0)
                node_imp[:, sl] = np.asarray(_impurity(total, impurity)[0])

            offset = 2**max_depth - 1
            m_nodes = 2**max_depth
            tot = rdd.mapPartitionsWithIndex(
                partials_op(max_depth, offset, m_nodes, False,
                            feature.copy(), threshold.copy())
            ).treeReduce(lambda a, b: a + b)
            tot = jnp.asarray(tot, dtype=jnp.float32)
            sl = slice(offset, offset + m_nodes)
            is_leaf[:, sl] = True
            leaf_value[:, sl, :] = np.asarray(_leaf_prediction(tot, impurity))
            imp_bottom, w_bottom = _impurity(tot, impurity)
            node_weight[:, sl] = np.asarray(w_bottom)
            node_imp[:, sl] = np.asarray(imp_bottom)
        finally:
            rdd.unpersist()

        if not classification:
            leaf_value = leaf_value + y_mean  # the core's add-back
        forest = Forest(
            jnp.asarray(feature),
            jnp.asarray(threshold),
            jnp.asarray(is_leaf),
            jnp.asarray(leaf_value),
            jnp.asarray(node_weight),
            jnp.asarray(node_gain),
            jnp.asarray(node_imp),
        )
        return forest, d, n_classes

    class TpuRandomForestClassifier(SparkEstimator, _TpuProbabilisticParams, _TpuEstimatorPersistence):
        numTrees = Param(Params._dummy(), "numTrees", "number of trees", TypeConverters.toInt)
        maxDepth = Param(Params._dummy(), "maxDepth", "max tree depth", TypeConverters.toInt)
        maxBins = Param(Params._dummy(), "maxBins", "max feature bins", TypeConverters.toInt)
        seed = Param(Params._dummy(), "seed", "random seed", TypeConverters.toInt)
        impurity = Param(Params._dummy(), "impurity", "gini or entropy", TypeConverters.toString)
        subsamplingRate = Param(Params._dummy(), "subsamplingRate", "row sampling rate per tree", TypeConverters.toFloat)
        bootstrap = Param(Params._dummy(), "bootstrap", "sample with replacement", TypeConverters.toBoolean)
        featureSubsetStrategy = Param(Params._dummy(), "featureSubsetStrategy", "features considered per split", TypeConverters.toString)

        def __init__(self, featuresCol="features", labelCol="label"):
            super().__init__()
            self._setDefault(
                numTrees=20, maxDepth=5, maxBins=32, seed=0, impurity="gini",
                subsamplingRate=1.0, bootstrap=True,
                featureSubsetStrategy="auto",
                featuresCol="features", labelCol="label",
                predictionCol="prediction", probabilityCol="probability",
                rawPredictionCol="rawPrediction",
            )
            self._set(featuresCol=featuresCol, labelCol=labelCol)

        def setNumTrees(self, value):
            return self._set(numTrees=value)

        def setMaxDepth(self, value):
            return self._set(maxDepth=value)

        def setMaxBins(self, value):
            return self._set(maxBins=value)

        def setSeed(self, value):
            return self._set(seed=value)

        def setImpurity(self, value):
            return self._set(impurity=value)

        def setSubsamplingRate(self, value):
            return self._set(subsamplingRate=value)

        def setBootstrap(self, value):
            return self._set(bootstrap=value)

        def setFeatureSubsetStrategy(self, value):
            return self._set(featureSubsetStrategy=value)

        def _fit(self, dataset):
            from spark_rapids_ml_tpu.models.random_forest import (
                RandomForestClassificationModel,
            )

            rdd = dataset.select(
                self.getOrDefault(self.featuresCol),
                self.getOrDefault(self.labelCol),
            ).rdd
            forest, d, n_classes = _fit_forest_rdd(
                rdd,
                n_trees=self.getOrDefault(self.numTrees),
                max_depth=self.getOrDefault(self.maxDepth),
                max_bins=self.getOrDefault(self.maxBins),
                seed=self.getOrDefault(self.seed),
                impurity=self.getOrDefault(self.impurity),
                classification=True,
                subsampling_rate=self.getOrDefault(self.subsamplingRate),
                bootstrap=self.getOrDefault(self.bootstrap),
                feature_subset=self.getOrDefault(self.featureSubsetStrategy),
            )
            core = RandomForestClassificationModel(
                None, forest, numFeatures=d, numClasses=n_classes
            )
            model = TpuRandomForestClassificationModel(core)
            for p in ("featuresCol", "labelCol", "predictionCol", "probabilityCol", "rawPredictionCol"):
                model._set(**{p: self.getOrDefault(getattr(self, p))})
            return model

    class TpuRandomForestClassificationModel(SparkModel, _TpuProbabilisticParams, _TpuCoreModelPersistence):
        def __init__(self, core_model=None):
            super().__init__()
            self._setDefault(
                featuresCol="features", labelCol="label",
                predictionCol="prediction", probabilityCol="probability",
                rawPredictionCol="rawPrediction",
            )
            self._core = core_model

        @property
        def numClasses(self):
            return self._core.numClasses

        def _transform(self, dataset):
            import functools

            from spark_rapids_ml_tpu.models.random_forest import _forest_depth
            from spark_rapids_ml_tpu.spark import executor_math

            f = self._core._forest
            forward = functools.partial(
                executor_math.forest_forward,
                np.asarray(f.feature),
                np.asarray(f.threshold, dtype=np.float64),
                np.asarray(f.is_leaf),
                np.asarray(f.leaf_value, dtype=np.float64),
                _forest_depth(f),
            )
            return _classifier_transform(forward, self._core.numClasses, self)(dataset)

        @staticmethod
        def _core_class():
            from spark_rapids_ml_tpu.models.random_forest import RandomForestClassificationModel

            return RandomForestClassificationModel

    class _TpuNeighborsBase(SparkEstimator, _TpuPredictorParams, _TpuEstimatorPersistence):
        """Shared surface of the neighbor estimators: fit collects the item
        vectors to the driver chip (the modern spark-rapids-ml deployment
        shape for its no-Spark-ML-equivalent families), and the model's
        ``kneighbors`` appends distances/indices array columns to a query
        DataFrame via one Arrow-batch search per partition.

        UNLIKE the classic families (numpy-only executors), the kneighbors
        UDF ships the accelerated index/model to executors — searches run
        the JAX kernels there, exactly as the modern reference requires
        cuML on its executors for these families."""

        k = Param(Params._dummy(), "k", "neighbors per query", TypeConverters.toInt)
        inputCol = Param(Params._dummy(), "inputCol", "item/query vector column", TypeConverters.toString)
        indexMode = Param(
            Params._dummy(), "indexMode",
            "collected (driver-chip index) | sharded (executor-local "
            "partition shards, treeReduce top-k merge)",
            TypeConverters.toString,
        )

        def setK(self, value):
            return self._set(k=value)

        def setInputCol(self, value):
            return self._set(inputCol=value)

        def setIndexMode(self, value):
            """``"sharded"`` keeps each partition's items ON ITS EXECUTOR
            as a local index shard (VERDICT r3 #5): queries broadcast,
            shard-local numpy top-k (executor_math.knn_shard_topk), one
            treeReduce candidate merge — the partition-local
            compute+merge shape of the reference's covariance path
            (RapidsRowMatrix.scala:170-201), so ANN/kNN capacity scales
            with the CLUSTER, not one chip's HBM. ``"collected"``
            (default) keeps the driver-chip accelerated index."""
            if value not in ("collected", "sharded"):
                raise ValueError(
                    f"indexMode must be collected|sharded, got {value!r}"
                )
            return self._set(indexMode=value)

        def _collect_items(self, dataset):
            return _collect_features(dataset, self.getOrDefault(self.inputCol))

        def _build_shards(self, dataset):
            """Per-partition (global_offset, items_block) RDD — items never
            leave their executors; only the per-partition COUNTS cross to
            the driver (to fix global row offsets)."""
            col_name = self.getOrDefault(self.inputCol)
            rows = dataset.select(col_name).rdd

            def to_block(_, it):
                xs = [np.asarray(r[0].toArray(), dtype=np.float64) for r in it]
                yield np.stack(xs) if xs else np.zeros((0, 0))

            blocks = rows.mapPartitionsWithIndex(to_block).cache()
            counts = blocks.mapPartitionsWithIndex(
                lambda i, it: [(i, sum(b.shape[0] for b in it))]
            ).collect()
            offsets = {}
            acc = 0
            for i, c in sorted(counts):
                offsets[i] = acc
                acc += c
            if acc == 0:
                raise ValueError("empty dataset")

            def attach_offset(i, it):
                for b in it:
                    if b.shape[0]:
                        yield (offsets[i], b)

            shards = blocks.mapPartitionsWithIndex(attach_offset).cache()
            # Materialize the shard cache, then drop the intermediate
            # blocks cache — keeping both would hold TWO copies of the
            # item set in executor storage for the model's lifetime.
            shards.count()
            blocks.unpersist()
            return shards, acc

    class _TpuNeighborsModelBase(SparkModel, _TpuPredictorParams):
        k = _TpuNeighborsBase.k
        inputCol = _TpuNeighborsBase.inputCol

        def __init__(self, core_model=None, shards=None, metric="euclidean"):
            super().__init__()
            self._setDefault(inputCol="features", k=5)
            self._core = core_model
            self._shards = shards  # (rdd of (offset, block), n_items) or None
            self._shard_metric = metric

        def kneighbors(self, dataset, k=None):
            """Append ``distances`` / ``indices`` array columns (original
            item row indices) to the query DataFrame."""
            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col, pandas_udf

            core = self._core
            k_eff = int(k if k is not None else self.getOrDefault(self.k))
            if self._shards is not None:
                return self._kneighbors_sharded(dataset, k_eff)

            @pandas_udf("array<double>")
            def knn_pairs(series):
                import pandas as pd

                if len(series) == 0:  # empty query partition
                    return pd.Series([], dtype=object)
                block = np.stack([np.asarray(v, dtype=np.float64) for v in series])
                d, i = core.kneighbors(block, k=k_eff)
                packed = np.concatenate(
                    [np.asarray(d, dtype=np.float64), np.asarray(i, dtype=np.float64)],
                    axis=1,
                )
                return pd.Series(list(packed))

            def slice_arr(lo, hi):
                @pandas_udf("array<double>")
                def s(series):
                    import pandas as pd

                    return pd.Series([np.asarray(v)[lo:hi] for v in series])

                return s

            @pandas_udf("array<long>")
            def indices_slice(series):
                import pandas as pd

                return pd.Series(
                    [
                        np.asarray(v)[k_eff : 2 * k_eff].astype(np.int64)
                        for v in series
                    ]
                )

            feats = vector_to_array(col(self.getOrDefault(self.inputCol)))
            tmp = "_tpu_knn"
            out = dataset.withColumn(tmp, knn_pairs(feats))
            out = out.withColumn("distances", slice_arr(0, k_eff)(col(tmp)))
            # Indices surface as INTEGERS (the reference's column type),
            # not float-coerced doubles.
            out = out.withColumn("indices", indices_slice(col(tmp)))
            return out.drop(tmp)

        def _kneighbors_sharded(self, dataset, k_eff):
            """Executor-sharded search (VERDICT r3 #5): the QUERY batch
            crosses to the driver once (queries are the small side of an
            ANN deployment), each item shard computes its local numpy
            top-k where it lives, and one treeReduce merges candidates —
            the item set NEVER crosses executor->driver. Results attach
            by query position via one pandas_udf pass, keyed on a
            per-partition offset map computed the same way the shards
            fixed theirs."""
            import pandas as pd
            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col, pandas_udf

            from spark_rapids_ml_tpu.spark.executor_math import (
                knn_merge_candidates,
                knn_shard_topk,
            )

            shards_rdd, n_items = self._shards
            if not 1 <= k_eff <= n_items:
                raise ValueError(f"k must be in [1, {n_items}], got {k_eff}")
            col_name = self.getOrDefault(self.inputCol)
            metric = self._shard_metric
            q_rows = [
                np.asarray(row[0].toArray(), dtype=np.float64)
                for row in dataset.select(col_name).rdd.toLocalIterator()
            ]
            if not q_rows:
                # Empty query set (routine after a filter): nothing to
                # search; the attach UDF below handles empty partitions.
                q = np.zeros((0, 1))
                packed = np.zeros((0, 2 * k_eff))
            else:
                q = np.stack(q_rows)

                def shard_topk(it):
                    for offset, block in it:
                        yield knn_shard_topk(q, block, offset, k_eff, metric)

                d, idx = shards_rdd.mapPartitions(shard_topk).treeReduce(
                    lambda a, b: knn_merge_candidates(a, b, k_eff)
                )
                packed = np.concatenate([d, idx.astype(np.float64)], axis=1)

            # Attach by CONTENT, not position: a bytes-keyed map from the
            # exact f64 query vector to its packed result, shipped as ONE
            # broadcast (handle-only task closures — the same contract
            # the transform closures follow). Positional attachment via
            # shared driver state would silently misalign on a real
            # multi-executor cluster; content keys are executor-safe, and
            # duplicate query vectors correctly share one result.
            res_bc = dataset.sparkSession.sparkContext.broadcast(
                {vec.tobytes(): row for vec, row in zip(q, packed)}
            )

            @pandas_udf("array<double>")
            def attach(series):
                if len(series) == 0:
                    return pd.Series([], dtype=object)
                res_map = res_bc.value
                return pd.Series(
                    [
                        res_map[np.asarray(v, dtype=np.float64).tobytes()]
                        for v in series
                    ]
                )

            def slice_arr(lo, hi, cast=None):
                @pandas_udf("array<double>" if cast is None else "array<long>")
                def s(series):
                    return pd.Series(
                        [
                            np.asarray(v)[lo:hi]
                            if cast is None
                            else np.asarray(v)[lo:hi].astype(np.int64)
                            for v in series
                        ]
                    )

                return s

            feats = vector_to_array(col(col_name))
            tmp = "_tpu_knn"
            out = dataset.withColumn(tmp, attach(feats))
            out = out.withColumn("distances", slice_arr(0, k_eff)(col(tmp)))
            out = out.withColumn(
                "indices", slice_arr(k_eff, 2 * k_eff, cast=True)(col(tmp))
            )
            return out.drop(tmp)

    class TpuNearestNeighbors(_TpuNeighborsBase):
        """Exact kNN (the modern spark-rapids-ml NearestNeighbors)."""

        metric = Param(Params._dummy(), "metric", "euclidean|sqeuclidean|cosine", TypeConverters.toString)

        def __init__(self, k=5, inputCol="features"):
            super().__init__()
            self._setDefault(k=5, inputCol="features", metric="euclidean",
                             indexMode="collected",
                             predictionCol="prediction", featuresCol="features",
                             labelCol="label")
            self._set(k=k, inputCol=inputCol)

        def setMetric(self, value):
            return self._set(metric=value)

        def _fit(self, dataset):
            from spark_rapids_ml_tpu.neighbors import NearestNeighbors

            metric = self.getOrDefault(self.metric)
            if self.getOrDefault(self.indexMode) == "sharded":
                shards = self._build_shards(dataset)
                model = TpuNearestNeighborsModel(
                    None, shards=shards, metric=metric
                )
            else:
                items = self._collect_items(dataset)
                core = (
                    NearestNeighbors()
                    .setK(self.getOrDefault(self.k))
                    .setMetric(metric)
                    .fit(items)
                )
                model = TpuNearestNeighborsModel(core)
            model._set(
                k=self.getOrDefault(self.k),
                inputCol=self.getOrDefault(self.inputCol),
            )
            return model

    class TpuNearestNeighborsModel(_TpuNeighborsModelBase):
        pass

    class TpuApproximateNearestNeighbors(_TpuNeighborsBase):
        """ANN — the modern spark-rapids-ml ANN family. Algorithms pass
        through to the core model: ivfflat | ivfpq | brute |
        brute_approx (the TPU-first hardware-top-k winner at
        single-chip scales — BASELINE.md config 7)."""

        algorithm = Param(
            Params._dummy(), "algorithm",
            "ivfflat|ivfpq|brute|brute_approx", TypeConverters.toString,
        )
        algoParams = Param(Params._dummy(), "algoParams", "algorithm parameters", TypeConverters.identity)

        def __init__(self, k=5, inputCol="features"):
            super().__init__()
            self._setDefault(k=5, inputCol="features", algorithm="ivfflat",
                             algoParams={}, indexMode="collected",
                             predictionCol="prediction",
                             featuresCol="features", labelCol="label")
            self._set(k=k, inputCol=inputCol)

        def setAlgorithm(self, value):
            return self._set(algorithm=value)

        def setAlgoParams(self, value):
            return self._set(algoParams=value)

        def _fit(self, dataset):
            from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors

            if self.getOrDefault(self.indexMode) == "sharded":
                # Sharded executors search their shard exactly (numpy) —
                # the brute contract; inverted lists are resident
                # driver-chip structures.
                if self.getOrDefault(self.algorithm) not in ("brute", "brute_approx"):
                    raise ValueError(
                        "indexMode='sharded' supports brute/brute_approx "
                        "(per-shard exact search + merge); inverted lists "
                        "need the collected driver-chip index"
                    )
                shards = self._build_shards(dataset)
                model = TpuApproximateNearestNeighborsModel(
                    None, shards=shards, metric="euclidean"
                )
            else:
                items = self._collect_items(dataset)
                core = (
                    ApproximateNearestNeighbors()
                    .setK(self.getOrDefault(self.k))
                    .setAlgorithm(self.getOrDefault(self.algorithm))
                    .setAlgoParams(dict(self.getOrDefault(self.algoParams)))
                    .fit(items)
                )
                model = TpuApproximateNearestNeighborsModel(core)
            model._set(
                k=self.getOrDefault(self.k),
                inputCol=self.getOrDefault(self.inputCol),
            )
            return model

    class TpuApproximateNearestNeighborsModel(_TpuNeighborsModelBase):
        pass

    class TpuDBSCAN(SparkEstimator, _TpuPredictorParams, _TpuEstimatorPersistence):
        """Density clustering (the modern spark-rapids-ml DBSCAN): fit
        computes labels for the TRAINING rows on the driver chip; the
        returned model's transform appends the cluster label column
        (-1 = noise) to the fitted dataset (cuML fit_predict semantics)."""

        eps = Param(Params._dummy(), "eps", "neighborhood radius", TypeConverters.toFloat)
        minSamples = Param(Params._dummy(), "minSamples", "core point threshold", TypeConverters.toInt)

        def __init__(self, featuresCol="features", predictionCol="prediction"):
            super().__init__()
            self._setDefault(
                eps=0.5, minSamples=5, featuresCol="features",
                labelCol="label", predictionCol="prediction",
            )
            self._set(featuresCol=featuresCol, predictionCol=predictionCol)

        def setEps(self, value):
            return self._set(eps=value)

        def setMinSamples(self, value):
            return self._set(minSamples=value)

        def _fit(self, dataset):
            from spark_rapids_ml_tpu.clustering import DBSCAN

            x = _collect_features(dataset, self.getOrDefault(self.featuresCol))
            core = (
                DBSCAN()
                .setEps(self.getOrDefault(self.eps))
                .setMinSamples(self.getOrDefault(self.minSamples))
                .fit(x)
            )
            model = TpuDBSCANModel(core)
            for p in ("featuresCol", "predictionCol"):
                model._set(**{p: self.getOrDefault(getattr(self, p))})
            return model

    class TpuDBSCANModel(SparkModel, _TpuPredictorParams, _TpuCoreModelPersistence):
        def __init__(self, core_model=None):
            super().__init__()
            self._setDefault(
                featuresCol="features", labelCol="label", predictionCol="prediction"
            )
            self._core = core_model
            # (core, callable): rebuilt if _core is ever replaced.
            self._apply = None

        @property
        def labels_(self):
            return self._core.labels_

        def _transform(self, dataset):
            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col

            if self._apply is None or self._apply[0] is not self._core:
                # Training rows must return the labels FIT assigned
                # (border assignment is expansion-order-dependent;
                # per-batch nearest-core re-prediction could relabel
                # them). Identical rows share identical epsilon-graph
                # adjacency, so a value lookup is exact for DBSCAN.
                # The lookup (training matrix + labels) ships as a
                # BROADCAST: one serialization total, a handle per task.
                bc = dataset.sparkSession.sparkContext.broadcast(
                    _FittedOrTransform(
                        np.asarray(self._core.fitted),
                        np.asarray(self._core.labels_, dtype=np.float64),
                        self._core.transform,
                    )
                )
                self._apply = (self._core, _BroadcastCall(bc))
            return dataset.withColumn(
                self.getOrDefault(self.predictionCol),
                _prediction_udf(self._apply[1])(
                    vector_to_array(col(self.getOrDefault(self.featuresCol)))
                ),
            )

        @staticmethod
        def _core_class():
            from spark_rapids_ml_tpu.models.dbscan import DBSCANModel

            return DBSCANModel

    class TpuUMAP(SparkEstimator, _TpuPredictorParams, _TpuEstimatorPersistence):
        """Manifold embedding (the modern spark-rapids-ml UMAP): fit learns
        the layout on the driver chip; transform appends the embedding
        array column — training rows return their fitted coordinates, new
        rows embed against the frozen training layout."""

        nNeighbors = Param(Params._dummy(), "nNeighbors", "neighborhood size", TypeConverters.toInt)
        nComponents = Param(Params._dummy(), "nComponents", "embedding dimension", TypeConverters.toInt)
        nEpochs = Param(Params._dummy(), "nEpochs", "optimization epochs (0 = auto)", TypeConverters.toInt)
        seed = Param(Params._dummy(), "seed", "random seed", TypeConverters.toInt)
        outputCol = Param(Params._dummy(), "outputCol", "embedding column", TypeConverters.toString)
        buildAlgo = Param(
            Params._dummy(), "buildAlgo",
            "kNN graph build: brute (exact) | brute_approx (hardware top-k)",
            TypeConverters.toString,
        )

        def __init__(self, featuresCol="features", outputCol="embedding"):
            super().__init__()
            self._setDefault(
                nNeighbors=15, nComponents=2, nEpochs=0, seed=0,
                buildAlgo="brute",
                featuresCol="features", labelCol="label",
                predictionCol="prediction", outputCol="embedding",
            )
            self._set(featuresCol=featuresCol, outputCol=outputCol)

        def setNNeighbors(self, value):
            return self._set(nNeighbors=value)

        def setNComponents(self, value):
            return self._set(nComponents=value)

        def setNEpochs(self, value):
            return self._set(nEpochs=value)

        def setSeed(self, value):
            return self._set(seed=value)

        def setOutputCol(self, value):
            return self._set(outputCol=value)

        def setBuildAlgo(self, value):
            return self._set(buildAlgo=value)

        def _fit(self, dataset):
            from spark_rapids_ml_tpu.manifold import UMAP

            core = (
                UMAP()
                .setNNeighbors(self.getOrDefault(self.nNeighbors))
                .setNComponents(self.getOrDefault(self.nComponents))
                .setNEpochs(self.getOrDefault(self.nEpochs))
                .setSeed(self.getOrDefault(self.seed))
                .setBuildAlgo(self.getOrDefault(self.buildAlgo))
                .fit(_collect_features(dataset, self.getOrDefault(self.featuresCol)))
            )
            model = TpuUMAPModel(core)
            model._set(
                featuresCol=self.getOrDefault(self.featuresCol),
                outputCol=self.getOrDefault(self.outputCol),
            )
            return model

    class TpuUMAPModel(SparkModel, _TpuPredictorParams, _TpuCoreModelPersistence):
        outputCol = TpuUMAP.outputCol

        def __init__(self, core_model=None):
            super().__init__()
            self._setDefault(
                featuresCol="features", labelCol="label",
                predictionCol="prediction", outputCol="embedding",
            )
            self._core = core_model
            # (core, callable): rebuilt if _core is ever replaced.
            self._apply = None

        @property
        def embedding(self):
            return self._core.embedding

        def _transform(self, dataset):
            from pyspark.ml.functions import array_to_vector, vector_to_array
            from pyspark.sql.functions import col, pandas_udf

            if self._apply is None or self._apply[0] is not self._core:
                # Training rows return their FITTED coordinates (the
                # fit_transform semantics of the reference) even though
                # Arrow batches slice the dataset below the core model's
                # whole-array shortcut. Ships as a BROADCAST: one
                # serialization total, a handle per task (VERDICT r3 #7).
                bc = dataset.sparkSession.sparkContext.broadcast(
                    _FittedOrTransform(
                        np.asarray(self._core.trainData),
                        np.asarray(self._core.embedding, dtype=np.float64),
                        self._core.transform,
                    )
                )
                self._apply = (self._core, _BroadcastCall(bc))
            apply = self._apply[1]

            @pandas_udf("array<double>")
            def embed(series):
                import pandas as pd

                if len(series) == 0:
                    return pd.Series([], dtype=object)
                block = np.stack(
                    [np.asarray(v, dtype=np.float64) for v in series]
                )
                return pd.Series(list(apply(block)))

            return dataset.withColumn(
                self.getOrDefault(self.outputCol),
                array_to_vector(
                    embed(vector_to_array(col(self.getOrDefault(self.featuresCol))))
                ),
            )

        @staticmethod
        def _core_class():
            from spark_rapids_ml_tpu.models.umap import UMAPModel

            return UMAPModel

    class TpuRandomForestRegressor(SparkEstimator, _TpuPredictorParams, _TpuEstimatorPersistence):
        numTrees = Param(Params._dummy(), "numTrees", "number of trees", TypeConverters.toInt)
        maxDepth = Param(Params._dummy(), "maxDepth", "max tree depth", TypeConverters.toInt)
        maxBins = Param(Params._dummy(), "maxBins", "max feature bins", TypeConverters.toInt)
        seed = Param(Params._dummy(), "seed", "random seed", TypeConverters.toInt)
        subsamplingRate = Param(Params._dummy(), "subsamplingRate", "row sampling rate per tree", TypeConverters.toFloat)
        bootstrap = Param(Params._dummy(), "bootstrap", "sample with replacement", TypeConverters.toBoolean)
        featureSubsetStrategy = Param(Params._dummy(), "featureSubsetStrategy", "features considered per split", TypeConverters.toString)

        def __init__(self, featuresCol="features", labelCol="label"):
            super().__init__()
            self._setDefault(
                numTrees=20, maxDepth=5, maxBins=32, seed=0,
                subsamplingRate=1.0, bootstrap=True,
                featureSubsetStrategy="auto",
                featuresCol="features", labelCol="label",
                predictionCol="prediction",
            )
            self._set(featuresCol=featuresCol, labelCol=labelCol)

        def setNumTrees(self, value):
            return self._set(numTrees=value)

        def setMaxDepth(self, value):
            return self._set(maxDepth=value)

        def setMaxBins(self, value):
            return self._set(maxBins=value)

        def setSeed(self, value):
            return self._set(seed=value)

        def setSubsamplingRate(self, value):
            return self._set(subsamplingRate=value)

        def setBootstrap(self, value):
            return self._set(bootstrap=value)

        def setFeatureSubsetStrategy(self, value):
            return self._set(featureSubsetStrategy=value)

        def _fit(self, dataset):
            from spark_rapids_ml_tpu.models.random_forest import (
                RandomForestRegressionModel,
            )

            rdd = dataset.select(
                self.getOrDefault(self.featuresCol),
                self.getOrDefault(self.labelCol),
            ).rdd
            forest, d, _ = _fit_forest_rdd(
                rdd,
                n_trees=self.getOrDefault(self.numTrees),
                max_depth=self.getOrDefault(self.maxDepth),
                max_bins=self.getOrDefault(self.maxBins),
                seed=self.getOrDefault(self.seed),
                impurity="variance",
                classification=False,
                subsampling_rate=self.getOrDefault(self.subsamplingRate),
                bootstrap=self.getOrDefault(self.bootstrap),
                feature_subset=self.getOrDefault(self.featureSubsetStrategy),
            )
            core = RandomForestRegressionModel(None, forest, numFeatures=d)
            model = TpuRandomForestRegressionModel(core)
            for p in ("featuresCol", "labelCol", "predictionCol"):
                model._set(**{p: self.getOrDefault(getattr(self, p))})
            return model

    class TpuRandomForestRegressionModel(SparkModel, _TpuPredictorParams, _TpuCoreModelPersistence):
        def __init__(self, core_model=None):
            super().__init__()
            self._setDefault(
                featuresCol="features", labelCol="label", predictionCol="prediction"
            )
            self._core = core_model

        def _transform(self, dataset):
            import functools

            from pyspark.ml.functions import vector_to_array
            from pyspark.sql.functions import col

            from spark_rapids_ml_tpu.models.random_forest import _forest_depth
            from spark_rapids_ml_tpu.spark import executor_math

            f = self._core._forest
            forward = functools.partial(
                executor_math.forest_forward_reg,
                np.asarray(f.feature),
                np.asarray(f.threshold, dtype=np.float64),
                np.asarray(f.is_leaf),
                np.asarray(f.leaf_value, dtype=np.float64),
                _forest_depth(f),
            )
            return dataset.withColumn(
                self.getOrDefault(self.predictionCol),
                _prediction_udf(forward)(
                    vector_to_array(col(self.getOrDefault(self.featuresCol)))
                ),
            )

        @staticmethod
        def _core_class():
            from spark_rapids_ml_tpu.models.random_forest import RandomForestRegressionModel

            return RandomForestRegressionModel
