"""Spark integration layer (gated on pyspark availability).

The reference IS a Spark plugin; this build's compute core is Spark-free
(JAX/XLA + native host runtime) with this subpackage providing the bridge:

  - ``discovery/get_tpus_resources.sh`` — executor TPU discovery script
    (the getGpusResources.sh analogue, README.md:83-86)
  - ``resources`` — task-to-chip binding (TaskContext GPU lookup analogue,
    RapidsRowMatrix.scala:171-175)
  - ``adapter`` — pyspark.ml-compatible estimator wrappers that run the
    per-partition accelerated kernels inside ``mapPartitions`` and reduce
    sufficient statistics through Spark, exactly the reference's
    distribution strategy (RapidsRowMatrix.scala:170-201)

pyspark is NOT required (and not present in the CI image); importing
``spark_rapids_ml_tpu.spark.adapter`` raises a clear error when absent.
"""

from spark_rapids_ml_tpu.spark.resources import (
    pin_process_to_chip,
    resolve_device_ordinal,
    task_tpu_address,
)

__all__ = ["pin_process_to_chip", "resolve_device_ordinal", "task_tpu_address"]
