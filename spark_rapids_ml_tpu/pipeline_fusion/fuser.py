"""The fuser: stage serving signatures -> one composite XLA program.

Three pieces:

- :func:`composite_kernel` builds (and caches, per chain of stage
  kernels) ONE Python callable that traces the whole stage chain. The
  cache makes the function object stable, so the bucketed AOT program
  cache in ``core/serving.py`` — whose key leads with the kernel's
  identity — hits across repeated ``serving_signature()`` calls and
  across distinct pipelines that share a chain shape.
- :func:`fuse_signatures` packs the per-stage signatures into a
  :class:`CompositeSignature`: prefixed static dicts (``s0_precision``,
  ``s1_n_classes``, ...) so every stage's config stays part of the
  program key, nested weight pytrees passed positionally, and an output
  spec derived by ``jax.eval_shape`` through the terminal stage's
  transform-contract selection.
- :func:`fuse_pipeline_stages` applies the chain rules to a
  ``PipelineModel``'s stages and either returns the composite or
  (non-strict) warns a structured :class:`FusionFallbackWarning` and
  returns None so the caller keeps the stage-at-a-time path.

The composite applies each stage's ``select`` (the stage's
transform-on-array contract — e.g. labels out of the logistic forward
triple) INSIDE the program: outputs the pipeline contract never exposes
are dead code to XLA, which is where the fused program's ledgered bytes
drop strictly below the sum of its staged parts.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from spark_rapids_ml_tpu.observability.events import emit
from spark_rapids_ml_tpu.serving.signature import ServingSignature
from spark_rapids_ml_tpu.utils.envknobs import env_choice
from spark_rapids_ml_tpu.utils.lockcheck import make_lock
from spark_rapids_ml_tpu.utils.tracing import bump_counter

FUSION_ENV = "TPUML_PIPELINE_FUSION"
FUSION_FIT_ENV = "TPUML_PIPELINE_FUSION_FIT"


def fusion_mode() -> str:
    """``auto`` (fuse array transforms when the whole chain is fusable)
    or ``off`` (always stage-at-a-time)."""
    return env_choice(FUSION_ENV, ("auto", "off"), "auto")


def fusion_fit_enabled() -> bool:
    """Whether ``Pipeline.fit`` may place a plain-array dataset on device
    once and feed every stage device-resident intermediates."""
    return env_choice(FUSION_FIT_ENV, ("auto", "off"), "auto") == "auto"


class FusionFallbackWarning(UserWarning):
    """A pipeline could not fuse; transform falls back stage-at-a-time.

    Structured: ``pipeline`` (uid), ``stage`` (index or None for
    chain-level reasons), ``reason`` — so callers and tests can assert
    WHY a chain degraded instead of pattern-matching message text.
    """

    def __init__(self, pipeline: str, reason: str, stage: Optional[int] = None):
        self.pipeline = pipeline
        self.reason = reason
        self.stage = stage
        where = f" (stage {stage})" if stage is not None else ""
        super().__init__(
            f"pipeline {pipeline} not fused{where}: {reason}; "
            "transform runs stage-at-a-time"
        )


@dataclass
class CompositeSignature(ServingSignature):
    """A fused pipeline's serving contract — a :class:`ServingSignature`
    (it slots into the registry/batcher/router unchanged) plus the chain
    provenance: which stage families it composes. The ``weights`` field
    is a tuple of per-stage weight pytrees, passed positionally to the
    composite kernel; ``static`` is the prefixed union of the stages'
    static dicts."""

    stage_names: Tuple[str, ...] = ()


#: Composite kernels by (stage kernels, stage selects): ONE function
#: object per chain shape, ever — the AOT program cache keys on it.
_COMPOSITE_KERNELS: Dict[tuple, Callable] = {}  # guarded-by: _KERNEL_LOCK
_KERNEL_LOCK = make_lock("pipeline_fusion.kernels")


def _demux_static(static: Dict[str, Any], n_stages: int) -> List[Dict[str, Any]]:
    """Split ``{"s0_precision": ..., "s1_n_classes": ...}`` back into
    per-stage static dicts (the inverse of the fuse-time prefixing)."""
    per: List[Dict[str, Any]] = [{} for _ in range(n_stages)]
    for key, value in static.items():
        idx, _, inner = key.partition("_")
        per[int(idx[1:])][inner] = value
    return per


def composite_kernel(
    kernels: Tuple[Callable, ...], selects: Tuple[Optional[Callable], ...]
) -> Callable:
    """The one traced callable for a stage chain: runs ``kernels[i]`` on
    the previous stage's (selected) output, applying each stage's
    transform-contract ``select`` in-program so downstream-dead outputs
    are eliminated by XLA rather than materialized and sliced on host."""
    key = (tuple(kernels), tuple(selects))
    with _KERNEL_LOCK:
        fused = _COMPOSITE_KERNELS.get(key)
        if fused is not None:
            return fused

    def _fused_pipeline(x, *stage_weights, **static):
        import jax

        per_stage = _demux_static(static, len(kernels))
        out: Any = x
        for i, kernel in enumerate(kernels):
            feed = out if i == 0 else jax.tree_util.tree_leaves(out)[0]
            out = kernel(feed, *stage_weights[i], **per_stage[i])
            if selects[i] is not None:
                out = selects[i](out)
        return out

    _fused_pipeline.__name__ = "fused_" + "__".join(
        getattr(k, "__name__", "kernel").lstrip("_") for k in kernels
    )
    _fused_pipeline.__qualname__ = _fused_pipeline.__name__
    with _KERNEL_LOCK:
        return _COMPOSITE_KERNELS.setdefault(key, _fused_pipeline)


def _feed_spec(sig: ServingSignature):
    """The (leaves, width) a stage hands its successor: the first leaf
    of its transform-contract output for a probe batch, or (None, None)
    when the stage cannot feed a downstream kernel (non-2-D, or a
    multi-leaf contract with no defined feed)."""
    import jax

    probe = sig.output_spec(8, sig.weights_dtype())
    if sig.select is not None:
        probe = jax.eval_shape(sig.select, probe)
    leaves = jax.tree_util.tree_leaves(probe)
    if len(leaves) != 1 or len(leaves[0].shape) != 2:
        return None, None
    return leaves[0], int(leaves[0].shape[1])


def fuse_signatures(
    sigs: Sequence[ServingSignature], *, name: Optional[str] = None
) -> CompositeSignature:
    """Compose stage signatures into one :class:`CompositeSignature`.

    Chain rules (the caller is expected to have verified them via
    :func:`fuse_pipeline_stages`; violations raise ``ValueError``):
    every non-terminal stage must yield a single 2-D output whose width
    matches the next stage's ``n_features``.
    """
    import jax

    if not sigs:
        raise ValueError("cannot fuse an empty stage chain")
    for i, sig in enumerate(sigs[:-1]):
        _, width = _feed_spec(sig)
        if width is None:
            raise ValueError(
                f"stage {i} ({sig.name}) does not produce a single 2-D "
                "feature block; it cannot feed a downstream stage"
            )
        if width != sigs[i + 1].n_features:
            raise ValueError(
                f"stage {i} ({sig.name}) emits width {width} but stage "
                f"{i + 1} ({sigs[i + 1].name}) expects "
                f"{sigs[i + 1].n_features} features"
            )

    kernels = tuple(s.kernel for s in sigs)
    selects = tuple(s.select for s in sigs)
    static = {
        f"s{i}_{k}": v for i, s in enumerate(sigs) for k, v in s.static.items()
    }
    last = sigs[-1]
    if last.select is None:
        out_spec = last.output_spec
    else:
        def out_spec(n, dtype, _last=last):
            return jax.eval_shape(_last.select, _last.output_spec(n, dtype))

    return CompositeSignature(
        kernel=composite_kernel(kernels, selects),
        weights=tuple(s.weights for s in sigs),
        static=static,
        name=name or ("fused:" + "+".join(s.name for s in sigs)),
        n_features=int(sigs[0].n_features),
        output_spec=out_spec,
        stage_names=tuple(s.name for s in sigs),
    )


def fuse_pipeline_stages(
    stages: Sequence[Any], *, pipeline: str, strict: bool = False
) -> Optional[CompositeSignature]:
    """Resolve every stage's ``serving_signature()`` and fuse the chain.

    Non-strict (the transform path): any unfusable link warns ONE
    structured :class:`FusionFallbackWarning` and returns None — the
    caller keeps the stage-at-a-time loop. Strict (the registry path,
    where a pipeline must BE a servable): the same condition raises
    ``TypeError``, matching the registry's contract for models without
    a serving signature.
    """

    def bail(reason: str, stage: Optional[int] = None):
        bump_counter("pipeline.fusion.fallback")
        emit(
            "pipeline_fusion", action="fallback", pipeline=pipeline,
            stage=stage, reason=reason,
        )
        if strict:
            raise TypeError(f"pipeline {pipeline} is not fusable: {reason}")
        warnings.warn(
            FusionFallbackWarning(pipeline, reason, stage), stacklevel=3
        )
        return None

    if not stages:
        return bail("pipeline has no stages")
    sigs: List[ServingSignature] = []
    for i, stage in enumerate(stages):
        sig_fn = getattr(stage, "serving_signature", None)
        if sig_fn is None:
            return bail(
                f"{type(stage).__name__} declares no serving_signature()", i
            )
        try:
            sigs.append(sig_fn())
        except Exception as exc:
            return bail(
                f"{type(stage).__name__}.serving_signature() failed: {exc}", i
            )
    for i, sig in enumerate(sigs[:-1]):
        _, width = _feed_spec(sig)
        if width is None:
            return bail(
                f"{sig.name} does not produce a single 2-D feature block", i
            )
        if width != sigs[i + 1].n_features:
            return bail(
                f"{sig.name} emits width {width} but {sigs[i + 1].name} "
                f"expects {sigs[i + 1].n_features} features", i,
            )
    fused = fuse_signatures(sigs)
    bump_counter("pipeline.fusion.fused")
    emit(
        "pipeline_fusion", action="fused", pipeline=pipeline,
        stages=list(fused.stage_names), name=fused.name,
    )
    return fused
