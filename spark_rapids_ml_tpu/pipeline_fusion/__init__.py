"""Pipeline fusion — multi-stage pipelines compiled as ONE XLA program.

A fitted ``PipelineModel`` used to transform operator-at-a-time: each
stage ran its own cached program and the intermediate features bounced
through host arrays between stages — the Spark shape Flare (PAPERS.md)
shows losing an order of magnitude to whole-query native compilation.
This package is the whole-query compiler for the serving plane: the
fuser composes the stages' ``serving_signature()`` kernels into one
jitted/AOT composite program (keyed into the bucketed program cache and
the cost ledger like any single-model kernel), so the chain executes
device-resident with host contact only at ingest and egress, and a
fused pipeline registers/warms/hot-swaps/routes through the serving
runtime as a single versioned model.
"""

from spark_rapids_ml_tpu.pipeline_fusion.fuser import (
    CompositeSignature,
    FusionFallbackWarning,
    composite_kernel,
    fuse_pipeline_stages,
    fuse_signatures,
    fusion_fit_enabled,
    fusion_mode,
)

__all__ = [
    "CompositeSignature",
    "FusionFallbackWarning",
    "composite_kernel",
    "fuse_pipeline_stages",
    "fuse_signatures",
    "fusion_fit_enabled",
    "fusion_mode",
]
